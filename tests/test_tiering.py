"""Tiered segment lifecycle (ISSUE 12, server/tiering.py).

The contracts under test:

1. WARM LAZINESS — a query touching 2 of 20 columns maps only those
   planes (asserted through the plane-load hook counters), matching
   ``PinotDataBuffer.mapFile`` semantics.
2. TIER PARITY — hot == warm == host bit-exact, solo AND on the 8-dev
   mesh, for sealed segments and alongside chunklet-promoted consuming
   segments; cold segments answer honestly-partial and converge to the
   full answer once hydrated.
3. COLD LIFECYCLE — demotion evicts local planes (metadata stays, the
   segment stays routable), ``numSegmentsCold`` surfaces in responses,
   the touch-triggered hydration restores full coverage via the PinotFS
   download (deadline-bounded, peer fallback).
4. POLICY — heat-ranked hot admission charges NARROW (ColPlan-modeled)
   bytes against the budget; idle+cold-rate segments demote to cold only
   when a durable deep-store copy exists.
5. SATELLITES — heat ``iter_all``/uncapped snapshot, typed
   ``UnresolvableSegmentLocation`` at ``add_segment``, and the
   controller's tier-aware replica-group rebalance moving ONLY
   temperature-flipped segments.
"""

import os
import time

import numpy as np
import pytest

from pinot_tpu.cluster.registry import (
    ClusterRegistry,
    InstanceInfo,
    Role,
    SegmentRecord,
    UnresolvableSegmentLocation,
)
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import (
    Controller,
    SegmentAssigner,
    aggregate_tiers,
)
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.server.heat import SegmentHeatTracker
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.server.tiering import (
    ColdSegmentRef,
    LazySegmentView,
    Tier,
    segment_plan_bytes,
)
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment

ROWS = 4096


def _build(base, n_segs=2, rows=ROWS, seed=7):
    rng = np.random.default_rng(seed)
    schema = Schema.build(
        name="tiers",
        dimensions=[("tag", DataType.STRING), ("mid", DataType.INT)],
        metrics=[("m", DataType.INT), ("f", DataType.DOUBLE)],
    )
    cfg = TableConfig(table_name="tiers")
    segs, all_cols = [], []
    for i in range(n_segs):
        cols = {
            "tag": np.array(["a", "b", "c"])[rng.integers(0, 3, rows)],
            "mid": rng.integers(0, 300, rows).astype(np.int32),
            "m": rng.integers(0, 10_000, rows).astype(np.int32),
            "f": np.round(rng.uniform(0, 100, rows), 3),
        }
        all_cols.append(cols)
        d = str(base / f"s{i}")
        build_segment(schema, cols, d, cfg, f"s{i}")
        segs.append(ImmutableSegment(d))
    return schema, cfg, segs, all_cols


@pytest.fixture(scope="module")
def table(tmp_path_factory):
    return _build(tmp_path_factory.mktemp("tiering"))


def _engine(segs, device="auto", table="tiers"):
    eng = QueryEngine() if device == "auto" \
        else QueryEngine(device_executor=device)
    for s in segs:
        eng.add_segment(table, s)
    return eng


PARITY_QUERIES = [
    "SELECT COUNT(*), SUM(m), MIN(m), MAX(m) FROM tiers WHERE tag = 'b'",
    "SELECT COUNT(*), AVG(m) FROM tiers WHERE mid IN (5, 250, 299)",
    "SELECT tag, COUNT(*), SUM(m) FROM tiers GROUP BY tag ORDER BY tag",
    "SELECT mid, COUNT(*), SUM(f) FROM tiers WHERE tag = 'c' "
    "GROUP BY mid ORDER BY mid LIMIT 10",
    "SELECT COUNT(*), DISTINCTCOUNT(tag) FROM tiers WHERE m > 2000",
]


def _rows_close(a, b):
    """Row-set equality with float tolerance (device f32 partial sums vs
    the host's f64 — the same comparison the narrow suite uses; integer
    and string cells must match exactly)."""
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if isinstance(va, float) or isinstance(vb, float):
                if not np.isclose(float(va), float(vb),
                                  rtol=1e-5, atol=1e-6):
                    return False
            elif va != vb:
                return False
    return True


class TestWarmLaziness:
    def test_query_maps_only_touched_planes(self, tmp_path):
        # 20 columns; a query touching 2 must map exactly those planes
        rng = np.random.default_rng(3)
        names = [f"c{i:02d}" for i in range(20)]
        schema = Schema.build(
            name="wide20", dimensions=[],
            metrics=[(n, DataType.INT) for n in names])
        cfg = TableConfig(table_name="wide20")
        cols = {n: rng.integers(0, 1000, 2048).astype(np.int32)
                for n in names}
        d = str(tmp_path / "w")
        build_segment(schema, cols, d, cfg, "w0")
        view = LazySegmentView(d)
        assert view.tier == Tier.WARM
        assert view.plane_loads == 0  # construction maps NO planes
        eng = _engine([view], device=None, table="wide20")
        r = eng.execute("SELECT SUM(c03) FROM wide20 WHERE c11 > 0")
        assert not r.get("exceptions"), r
        touched = {f.split(".")[0] for f in view.planes_loaded}
        assert touched <= {"c03", "c11"}, view.planes_loaded
        assert {"c03", "c11"} & touched
        # the other 18 columns were never mapped
        assert not touched & (set(names) - {"c03", "c11"})

    def test_release_planes_drops_caches(self, table):
        _, _, segs, _ = table
        view = LazySegmentView(segs[0].dir)
        eng = _engine([view], device=None)
        eng.execute("SELECT SUM(m) FROM tiers")
        assert view._fwd_cache
        view.release_planes()
        assert not view._fwd_cache and not view._dict_cache
        # still queryable after release (planes re-map on demand)
        r = eng.execute("SELECT COUNT(*) FROM tiers")
        assert r["resultTable"]["rows"][0][0] == ROWS

    def test_plan_bytes_narrow_aware(self, table):
        _, _, segs, _ = table
        cost = segment_plan_bytes(segs[0])
        # tag: card 3 -> 1B; mid: card<=300 -> 2B; m: range<2^16 -> 2B;
        # f: device f32 -> 4B. The legacy logical widths would be 4+4+4+8.
        assert cost == ROWS * (1 + 2 + 2 + 4)
        wide = ROWS * (4 + 4 + 4 + 8)
        assert cost * 2 < wide  # the narrow-aware charge admits >2x more


class TestHeatFullIteration:
    def test_iter_all_uncapped(self):
        t = SegmentHeatTracker(half_life_s=60)
        now = time.time()
        for i in range(40):
            t.note("tab", f"seg{i}", bytes_scanned=10, now=now - i)
        capped = t.snapshot(now=now)
        assert len(capped["tab"]) == 32  # heartbeat form stays bounded
        full = t.snapshot(top_per_table=None, now=now)
        assert len(full["tab"]) == 40
        seen = {(tt, s) for tt, s, _ in t.iter_all(now=now)}
        assert len(seen) == 40
        # decayed view is consistent between the two exports
        for tt, s, rec in t.iter_all(now=now):
            assert rec["rate"] == pytest.approx(
                full[tt][s]["rate"], abs=1e-3)


class TestLocationValidation:
    def test_unknown_scheme_typed_error(self):
        reg = ClusterRegistry()
        with pytest.raises(UnresolvableSegmentLocation):
            reg.add_segment(SegmentRecord(
                name="x", table="t", location="bogus://b/k"), [])

    def test_known_and_bare_locations_pass(self, tmp_path):
        reg = ClusterRegistry()
        for loc in ("", str(tmp_path / "d"), f"file://{tmp_path}/d",
                    "s3://bucket/seg", "gs://bucket/seg",
                    "hdfs://nn:8020/seg"):
            reg.add_segment(SegmentRecord(
                name=f"x{hash(loc) & 0xffff}", table="t", location=loc), [])


class TestTierParity:
    @pytest.mark.parametrize("sql", PARITY_QUERIES)
    def test_hot_equals_warm_equals_host(self, table, sql):
        _, _, segs, _ = table
        hot = _engine(segs)
        warm = _engine([LazySegmentView(s.dir) for s in segs])
        host = _engine(segs, device=None)
        rh, rw, ro = hot.execute(sql), warm.execute(sql), host.execute(sql)
        for r in (rh, rw, ro):
            assert not r.get("exceptions"), r
        # warm and host are both host scans: EXACT; hot (device) floats
        # compare at the f32-partial tolerance like the narrow suite
        assert rw["resultTable"]["rows"] == ro["resultTable"]["rows"]
        assert _rows_close(rh["resultTable"]["rows"],
                           ro["resultTable"]["rows"])

    def test_mixed_hot_warm_batch(self, table):
        # one hot + one warm segment of the SAME table: device batch for
        # the hot one, host scan for the warm one, merged partials
        _, _, segs, all_cols = table
        mixed = _engine([segs[0], LazySegmentView(segs[1].dir)])
        host = _engine(segs, device=None)
        for sql in PARITY_QUERIES:
            rm, ro = mixed.execute(sql), host.execute(sql)
            assert _rows_close(rm["resultTable"]["rows"],
                               ro["resultTable"]["rows"]), sql

    def test_mesh_parity(self, table):
        from pinot_tpu.engine.device import DeviceExecutor
        from pinot_tpu.parallel.mesh import make_mesh

        _, _, segs, _ = table
        mesh_hot = _engine(segs, DeviceExecutor(mesh=make_mesh(8)))
        mesh_mixed = _engine(
            [segs[0], LazySegmentView(segs[1].dir)],
            DeviceExecutor(mesh=make_mesh(8)))
        host = _engine(segs, device=None)
        for sql in PARITY_QUERIES[:3]:
            r1 = mesh_hot.execute(sql)
            r2 = mesh_mixed.execute(sql)
            ro = host.execute(sql)
            assert _rows_close(r1["resultTable"]["rows"],
                               ro["resultTable"]["rows"]), sql
            assert _rows_close(r2["resultTable"]["rows"],
                               ro["resultTable"]["rows"]), sql

    def test_warm_alongside_chunklet_promoted_consuming(self, table):
        # a warm sealed segment + a consuming segment with promoted
        # chunklets: the tier routing must not disturb the chunklet split
        from pinot_tpu.common.table_config import ChunkletConfig
        from pinot_tpu.storage.mutable import MutableSegment

        schema = Schema.build(
            name="tiers",
            dimensions=[("tag", DataType.STRING),
                        ("mid", DataType.INT)],
            metrics=[("m", DataType.INT), ("f", DataType.DOUBLE)],
        )
        cfg = TableConfig(
            table_name="tiers",
            chunklets=ChunkletConfig(enabled=True, rows_per_chunklet=1024,
                                     device_min_rows=0))
        rng = np.random.default_rng(5)
        mseg = MutableSegment(schema, "consuming0", cfg)
        rows = [{"tag": ["a", "b", "c"][int(rng.integers(0, 3))],
                 "mid": int(rng.integers(0, 300)),
                 "m": int(rng.integers(0, 10_000)),
                 "f": float(np.round(rng.uniform(0, 100), 3))}
                for _ in range(3000)]
        mseg.index_batch(rows)
        mseg.chunklet_index.promote()
        _, _, segs, _ = table
        warm = LazySegmentView(segs[0].dir)
        tiered = _engine([warm, mseg])
        plain = _engine([segs[0], mseg], device=None)
        for sql in PARITY_QUERIES[:3]:
            rt, rp = tiered.execute(sql), plain.execute(sql)
            assert _rows_close(rt["resultTable"]["rows"],
                               rp["resultTable"]["rows"]), sql

    def test_multistage_over_cold_segment(self, table, tmp_path):
        # stage-1 leaf scans skip cold segments honestly too (query2)
        schema_d = Schema.build(
            name="dimt", dimensions=[("tag", DataType.STRING),
                                     ("label", DataType.STRING)],
            metrics=[])
        cfg_d = TableConfig(table_name="dimt", is_dim_table=True)
        dd = str(tmp_path / "dim")
        build_segment(schema_d, {
            "tag": np.array(["a", "b", "c"]),
            "label": np.array(["A", "B", "C"])}, dd, cfg_d, "d0")
        _, _, segs, _ = table
        eng = QueryEngine()
        eng.add_segment("tiers", segs[0])
        eng.add_segment("tiers",
                        ColdSegmentRef("tiers", segs[1].metadata,
                                       segs[1].dir))
        eng.add_segment("dimt", ImmutableSegment(dd))
        eng.table("dimt").is_dim_table = True
        r = eng.execute(
            "SELECT d.label, SUM(t.m) FROM tiers t JOIN dimt d "
            "ON t.tag = d.tag GROUP BY d.label ORDER BY d.label")
        assert not r.get("exceptions"), r
        assert r["numSegmentsCold"] == 1
        # rows cover the one live segment only
        warm_only = QueryEngine(device_executor=None)
        warm_only.add_segment("tiers", segs[0])
        warm_only.add_segment("dimt", ImmutableSegment(dd))
        warm_only.table("dimt").is_dim_table = True
        ref = warm_only.execute(
            "SELECT d.label, SUM(t.m) FROM tiers t JOIN dimt d "
            "ON t.tag = d.tag GROUP BY d.label ORDER BY d.label")
        assert r["resultTable"]["rows"] == ref["resultTable"]["rows"]

    def test_all_cold_honest_empty(self, table):
        _, _, segs, _ = table
        refs = [ColdSegmentRef("tiers", s.metadata, s.dir) for s in segs]
        eng = _engine(refs)
        r = eng.execute("SELECT COUNT(*), SUM(m) FROM tiers")
        assert not r.get("exceptions"), r
        assert r["numSegmentsCold"] == len(segs)
        assert r["resultTable"]["rows"][0][0] == 0
        assert r["totalDocs"] == sum(s.n_docs for s in segs)
        # group-by + distinct shapes synthesize empty too
        for sql in ("SELECT tag, COUNT(*) FROM tiers GROUP BY tag",
                    "SELECT DISTINCT tag FROM tiers"):
            r = eng.execute(sql)
            assert not r.get("exceptions"), (sql, r)
            assert r["resultTable"]["rows"] == []


def _wait(cond, timeout=10.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.05)
    return False


class TestColdLifecycle:
    @pytest.fixture()
    def cluster(self, tmp_path):
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "deep"))
        server = ServerInstance(
            "srv_tier", registry, str(tmp_path / "srv"),
            device_executor=None,
            tier_overrides={"pinot.server.tier.enabled": True,
                            # ticks only run when we call them
                            "pinot.server.tier.interval.ms": 3_600_000})
        server.start()
        from pinot_tpu.broker.broker import Broker

        broker = Broker(registry, timeout_s=10.0)
        yield registry, controller, server, broker
        broker.close()
        server.stop()

    def _push(self, tmp_path, controller, n=3, rows=2000):
        schema = Schema.build(
            name="sales", dimensions=[("k", DataType.STRING)],
            metrics=[("v", DataType.INT)])
        cfg = TableConfig(table_name="sales")
        controller.add_table(cfg, schema)
        rng = np.random.default_rng(1)
        total = 0
        for i in range(n):
            cols = {"k": np.array(["x", "y"])[rng.integers(0, 2, rows)],
                    "v": rng.integers(0, 100, rows).astype(np.int32)}
            total += int(cols["v"].sum())
            d = str(tmp_path / f"up{i}")
            build_segment(schema, cols, d, cfg, f"sales_s{i}")
            controller.upload_segment("sales", d)
        return total

    def test_cold_demote_query_hydrate(self, cluster, tmp_path):
        registry, controller, server, broker = cluster
        total = self._push(tmp_path, controller)
        assert _wait(lambda: len(getattr(
            server.engine.tables.get("sales_OFFLINE"), "segments", ()))
            == 3)
        r = broker.execute("SELECT SUM(v) FROM sales")
        assert r["resultTable"]["rows"][0][0] == total
        assert r["numSegmentsCold"] == 0

        tdm = server.engine.tables["sales_OFFLINE"]
        name = sorted(tdm.segments)[0]
        assert server.tiers.demote_to_cold("sales_OFFLINE", name)
        seg_dir = tdm.segments[name].dir
        # planes evicted, metadata kept, segment still hosted + routable
        assert sorted(os.listdir(seg_dir)) == [
            "creation.meta.json", "metadata.json"]
        assert name in tdm.segments
        assert getattr(tdm.segments[name], "is_cold", False)

        r2 = broker.execute("SELECT SUM(v) FROM sales")
        assert r2["numSegmentsCold"] == 1
        assert r2["partialResult"] is True
        assert r2["resultTable"]["rows"][0][0] < total  # honest partial
        assert r2["totalDocs"] == 6000  # cold docs still counted

        # the touch scheduled hydration: converges to the full answer
        assert server.tiers.wait_hydrated("sales_OFFLINE", name, 15)
        r3 = broker.execute("SELECT SUM(v) FROM sales")
        assert r3["numSegmentsCold"] == 0
        assert r3["resultTable"]["rows"][0][0] == total
        assert server.tiers.hydrations == 1
        # hydrated segments land WARM (lazily mmap'd)
        assert tdm.segments[name].tier == Tier.WARM

    def test_demote_refuses_without_durable_copy(self, cluster, tmp_path):
        registry, controller, server, broker = cluster
        self._push(tmp_path, controller, n=1)
        assert _wait(lambda: len(getattr(
            server.engine.tables.get("sales_OFFLINE"), "segments", ()))
            == 1)
        tdm = server.engine.tables["sales_OFFLINE"]
        name = sorted(tdm.segments)[0]
        # blank out the record's location: demotion must refuse rather
        # than evict the only copy
        recs = registry.segments("sales_OFFLINE")
        rec = recs[name]
        rec.location = ""
        registry.add_segment(rec, [server.instance_id],
                             merge_instances=True)
        assert not server.tiers.demote_to_cold("sales_OFFLINE", name)
        assert not getattr(tdm.segments[name], "is_cold", False)

    def test_tick_policy_hot_admission_and_cold_idle(self, cluster,
                                                     tmp_path):
        registry, controller, server, broker = cluster
        self._push(tmp_path, controller, n=3)
        assert _wait(lambda: len(getattr(
            server.engine.tables.get("sales_OFFLINE"), "segments", ()))
            == 3)
        tiers = server.tiers
        tiers.cold_idle_s = 30.0
        tiers.cold_max_rate = 0.5
        now = time.time()
        names = sorted(server.engine.tables["sales_OFFLINE"].segments)
        # a first tick an hour ago establishes the first-seen baseline
        # (a segment idles from its LOAD, not from the epoch)
        tiers.tick(now=now - 3600)
        # hot-rate access for names[0]; one stale access for names[1];
        # one recentish access for names[2] (rate above the cold cut)
        for _ in range(10):
            server.heat.note("sales_OFFLINE", names[0], 1000, now=now)
        server.heat.note("sales_OFFLINE", names[1], 1000, now=now - 3600)
        server.heat.note("sales_OFFLINE", names[2], 1000, now=now - 60)
        applied = tiers.tick(now=now)
        snap = tiers.snapshot()["sales_OFFLINE"]
        # no device on this server -> hot budget 0: even the hottest
        # segment serves warm; the hour-stale one went cold; the
        # recently-touched one keeps enough decayed rate to stay warm
        assert snap[names[0]] == Tier.WARM
        assert snap[names[1]] == Tier.COLD
        assert snap[names[2]] == Tier.WARM
        assert names[1] in applied["to_cold"]

    def test_demote_refuses_file_uri_self_copy(self, cluster, tmp_path):
        # review hardening: a file:// URI pointing at the server's own
        # working copy must refuse demotion like a bare path does
        registry, controller, server, broker = cluster
        self._push(tmp_path, controller, n=1)
        assert _wait(lambda: len(getattr(
            server.engine.tables.get("sales_OFFLINE"), "segments", ()))
            == 1)
        tdm = server.engine.tables["sales_OFFLINE"]
        name = sorted(tdm.segments)[0]
        rec = registry.segments("sales_OFFLINE")[name]
        rec.location = "file://" + tdm.segments[name].dir
        registry.add_segment(rec, [server.instance_id],
                             merge_instances=True)
        assert not server.tiers.demote_to_cold("sales_OFFLINE", name)
        assert not getattr(tdm.segments[name], "is_cold", False)

    def test_budget_scale_recovers_under_hit_dominated_churn(
            self, cluster, tmp_path):
        # review hardening: a trickle of natural misses must not pin the
        # effective budget at the 0.25x floor forever
        registry, controller, server, broker = cluster
        tiers = server.tiers

        class FakeDev:
            MAX_CACHED_BYTES = 1000
            batch_hits = 0
            batch_misses = 0

        dev = FakeDev()
        server.engine.device = dev
        tiers.hot_budget_bytes = 1000
        tiers._budget_scale = 0.25
        dev.batch_hits, dev.batch_misses = 100, 1  # hit-dominated
        tiers._last_hits = tiers._last_misses = 0
        b1 = tiers._effective_budget()
        assert b1 > 250  # recovered past the floor
        dev.batch_hits, dev.batch_misses = 110, 30  # miss-heavy-ish but
        b2 = tiers._effective_budget()               # dm(29) < dh(10)? no:
        # dh=10, dm=29 -> contraction
        assert b2 < b1

    def test_heartbeat_carries_tiers_and_controller_aggregates(
            self, cluster, tmp_path):
        registry, controller, server, broker = cluster
        self._push(tmp_path, controller, n=2)
        assert _wait(lambda: len(getattr(
            server.engine.tables.get("sales_OFFLINE"), "segments", ()))
            == 2)
        name = sorted(server.engine.tables["sales_OFFLINE"].segments)[0]
        assert server.tiers.demote_to_cold("sales_OFFLINE", name)
        server.registry.heartbeat(
            server.instance_id, tiers=server.tiers.snapshot())
        agg = controller.table_tiers("sales")
        assert agg["segments"][name]["tier"] == Tier.COLD
        assert agg["instancesReporting"] == 1


class TestTieredRebalance:
    def _registry_with_table(self, n_servers=4, n_segments=8,
                             replication=2):
        reg = ClusterRegistry()
        for i in range(n_servers):
            reg.register_instance(
                InstanceInfo(f"s{i}", Role.SERVER, grpc_port=9000 + i))
        schema = Schema.build(name="t",
                              dimensions=[("k", DataType.STRING)],
                              metrics=[])
        cfg = TableConfig(table_name="t", replication=replication)
        reg.add_table(cfg, schema, key="t_OFFLINE")
        for i in range(n_segments):
            reg.add_segment(
                SegmentRecord(name=f"seg{i}", table="t_OFFLINE",
                              n_docs=10), [])
        return reg

    def test_cold_flip_moves_only_flipped_segment(self):
        reg = self._registry_with_table()
        assigner = SegmentAssigner(reg)
        base = assigner.rebalance_replica_groups("t_OFFLINE", 2)
        assert all(len(v) == 2 for v in base.values())

        # steady state: all-hot tiered pass publishes NOTHING
        gen0 = reg.routing_generation()
        same = assigner.rebalance_tiered(
            "t_OFFLINE", 2, {f"seg{i}": Tier.HOT for i in range(8)})
        assert {k: sorted(v) for k, v in same.items()} == \
               {k: sorted(v) for k, v in base.items()}
        assert reg.routing_generation() == gen0

        # cold flip: exactly the flipped segment trims, keeping a
        # current replica (the copy already on disk)
        after = assigner.rebalance_tiered("t_OFFLINE", 2,
                                          {"seg3": Tier.COLD})
        moved = [s for s in base
                 if sorted(base[s]) != sorted(after.get(s, ()))]
        assert moved == ["seg3"]
        assert len(after["seg3"]) == 1
        assert after["seg3"][0] in base["seg3"]

        # flip back: only it re-expands
        restored = assigner.rebalance_tiered("t_OFFLINE", 2,
                                             {"seg3": Tier.HOT})
        moved = [s for s in after
                 if sorted(after[s]) != sorted(restored.get(s, ()))]
        assert moved == ["seg3"]
        assert len(restored["seg3"]) == 2

    def test_aggregate_tiers_hottest_replica_wins(self):
        reg = self._registry_with_table(n_servers=2)
        reg.heartbeat("s0", tiers={"t_OFFLINE": {"seg0": Tier.COLD}})
        reg.heartbeat("s1", tiers={"t_OFFLINE": {"seg0": Tier.HOT}})
        agg = aggregate_tiers(reg, "t_OFFLINE")
        assert agg["segments"]["seg0"]["tier"] == Tier.HOT
        assert agg["segments"]["seg0"]["instances"] == {
            "s0": Tier.COLD, "s1": Tier.HOT}
