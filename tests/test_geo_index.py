"""Geo grid index (H3 index role) + ST_AREA/ST_POLYGON/WKB functions.

Reference analogs: ImmutableH3IndexReader + H3IndexFilterOperator,
StAreaFunction, StPolygonFunction, ST_GeomFromWKB/ST_AsBinary.
"""

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.ops import geo
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.geoindex import GeoGridIndex
from pinot_tpu.storage.segment import ImmutableSegment


class TestGeoFunctions:
    def test_st_area_of_one_degree_cell(self):
        # 1°x1° at the equator ≈ 12,364 km² (спherical)
        wkt = "POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))"
        area = geo.st_area([wkt])[0]
        assert abs(area - 12.36e9) / 12.36e9 < 0.01

    def test_st_polygon_validates(self):
        out = geo.st_polygon(["POLYGON ((0 0, 1 0, 1 1, 0 0))"])
        assert "POLYGON" in out[0]
        with pytest.raises(ValueError):
            geo.st_polygon(["POINT (1 2)"])

    def test_wkb_roundtrip(self):
        pts = geo.st_point([12.5, -30.25], [41.0, 80.5])
        wkb = geo.st_as_binary(pts)
        assert all(isinstance(b, bytes) and len(b) == 21 for b in wkb)
        back = geo.st_geom_from_wkb(wkb)
        lon, lat = geo.parse_points(back)
        np.testing.assert_allclose(lon, [12.5, -30.25])
        np.testing.assert_allclose(lat, [41.0, 80.5])


class TestGridIndex:
    def test_candidates_cover_circle(self):
        rng = np.random.default_rng(9)
        lon = rng.uniform(-10, 10, 5000)
        lat = rng.uniform(40, 60, 5000)
        pts = geo.st_point(lon, lat)
        idx = GeoGridIndex.build(pts)
        qlon, qlat, r = 2.0, 50.0, 30_000.0
        cand = set(idx.candidate_docs(qlon, qlat, r).tolist())
        d = geo.haversine_m(lon, lat, qlon, qlat)
        true_matches = set(np.nonzero(d <= r)[0].tolist())
        assert true_matches <= cand  # superset: no true match missed
        assert len(cand) < 5000 / 4  # and it actually narrows

    def test_save_load(self, tmp_path):
        pts = geo.st_point([0.1, 0.2, 5.0], [0.1, 0.2, 5.0])
        GeoGridIndex.build(pts).save(str(tmp_path), "p")
        idx = GeoGridIndex.load(str(tmp_path), "p")
        cand = idx.candidate_docs(0.15, 0.15, 50_000)
        assert set(cand.tolist()) >= {0, 1}


@pytest.fixture(scope="module")
def engines(tmp_path_factory):
    rng = np.random.default_rng(12)
    n = 40_000
    lon = rng.uniform(-5, 5, n)
    lat = rng.uniform(45, 55, n)
    cols = {
        "loc": geo.st_point(lon, lat),
        "v": rng.integers(0, 100, n).astype(np.int32),
    }
    schema = Schema.build(name="pois",
                          dimensions=[("loc", DataType.STRING)],
                          metrics=[("v", DataType.INT)])
    base = tmp_path_factory.mktemp("geo")
    with_idx = QueryEngine(device_executor=None)
    without = QueryEngine(device_executor=None)
    build_segment(schema, cols, str(base / "i"), TableConfig(
        table_name="pois",
        indexing=IndexingConfig(h3_index_columns=["loc"])), "s0")
    build_segment(schema, cols, str(base / "p"),
                  TableConfig(table_name="pois"), "s0")
    with_idx.add_segment("pois", ImmutableSegment(str(base / "i")))
    without.add_segment("pois", ImmutableSegment(str(base / "p")))
    return with_idx, without


GEO_QUERIES = [
    "SELECT COUNT(*), SUM(v) FROM pois WHERE "
    "ST_DISTANCE(loc, ST_POINT(1.5, 50.0)) < 20000",
    "SELECT COUNT(*) FROM pois WHERE "
    "ST_DISTANCE(ST_POINT(0.0, 48.0), loc) < 50000",
    # ring: lower+upper bound
    "SELECT COUNT(*) FROM pois WHERE "
    "ST_DISTANCE(loc, ST_POINT(2.0, 51.0)) BETWEEN 10000 AND 40000",
    # empty region
    "SELECT COUNT(*) FROM pois WHERE "
    "ST_DISTANCE(loc, ST_POINT(120.0, 10.0)) < 1000",
]


class TestGeoIndexQueries:
    @pytest.mark.parametrize("sql", GEO_QUERIES)
    def test_indexed_matches_scan(self, engines, sql):
        with_idx, without = engines
        a = with_idx.execute(sql)
        b = without.execute(sql)
        assert not a.get("exceptions"), a
        assert a["resultTable"]["rows"] == b["resultTable"]["rows"], sql

    def test_index_consulted(self, engines, monkeypatch):
        with_idx, _ = engines
        from pinot_tpu.storage import geoindex

        calls = []
        real = geoindex.GeoGridIndex.candidate_docs

        def spy(self, lon, lat, r):
            out = real(self, lon, lat, r)
            calls.append(len(out))
            return out

        monkeypatch.setattr(geoindex.GeoGridIndex, "candidate_docs", spy)
        r = with_idx.execute(GEO_QUERIES[0])
        assert not r.get("exceptions"), r
        assert calls and calls[0] < 40_000  # pruned below full scan
