"""FIRSTWITHTIME / LASTWITHTIME: the argmax-by-time combine family.

Reference: pinot-core/.../query/aggregation/function/
FirstWithTimeAggregationFunction.java:1, LastWithTimeAggregationFunction.java.
Tie-break divergence (largest value wins on equal times) is documented on
FirstLastWithTimeSpec; the oracle here implements the same rule.
"""

import os

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.engine.datatable import decode, encode
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment


def _oracle(df, is_first):
    """Per-key (best value): min/max time, ties -> max value."""
    out = {}
    for k, grp in df.groupby("k"):
        t = grp["ts"]
        best_t = t.min() if is_first else t.max()
        out[k] = grp.loc[t == best_t, "v"].max()
    return out


@pytest.fixture(scope="module")
def segments(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fwt")
    schema = Schema.build(
        name="t", dimensions=[("k", DataType.STRING)],
        metrics=[("v", DataType.LONG), ("ts", DataType.LONG)])
    rng = np.random.default_rng(7)
    # deliberate time ties: ts drawn from a SMALL range so most (k, ts)
    # pairs collide and the tie-break rule is actually exercised
    df = pd.DataFrame({
        "k": np.array(["a", "b", "c", "d"])[rng.integers(0, 4, 5000)],
        "v": rng.integers(-50, 50, 5000).astype(np.int64),
        "ts": rng.integers(0, 40, 5000).astype(np.int64),
    })
    segs = []
    for i in range(3):
        part = df.iloc[i * 1700: (i + 1) * 1700]
        segs.append(build_segment(
            schema, {c: part[c].to_numpy() for c in part},
            os.path.join(str(tmp), f"s{i}"), segment_name=f"s{i}"))
    return df, segs


def _engine(segs, device):
    eng = QueryEngine(device_executor="auto" if device else None)
    for s in segs:
        eng.add_segment("t", s)
    return eng


@pytest.mark.parametrize("device", [False, True])
@pytest.mark.parametrize("is_first", [False, True])
def test_group_by_matches_oracle(segments, device, is_first):
    df, segs = segments
    fn = "FIRSTWITHTIME" if is_first else "LASTWITHTIME"
    eng = _engine(segs, device)
    r = eng.execute(
        f"SELECT k, {fn}(v, ts, 'LONG') FROM t GROUP BY k ORDER BY k")
    assert not r.get("exceptions"), r
    want = _oracle(df, is_first)
    got = {row[0]: row[1] for row in r["resultTable"]["rows"]}
    assert set(got) == set(want)
    for k in want:
        assert got[k] == want[k], (k, got[k], want[k])


@pytest.mark.parametrize("device", [False, True])
def test_scalar_and_filtered(segments, device):
    df, segs = segments
    eng = _engine(segs, device)
    r = eng.execute("SELECT LASTWITHTIME(v, ts, 'LONG'), "
                    "FIRSTWITHTIME(v, ts, 'LONG') FROM t WHERE k = 'b'")
    assert not r.get("exceptions"), r
    sub = df[df.k == "b"]
    want_last = sub.loc[sub.ts == sub.ts.max(), "v"].max()
    want_first = sub.loc[sub.ts == sub.ts.min(), "v"].max()
    assert r["resultTable"]["rows"][0] == [want_last, want_first]


def test_device_host_identical(segments):
    """Bit-for-bit agreement between backends (the deterministic tie-break
    is what makes this assertable)."""
    _, segs = segments
    sql = ("SELECT k, LASTWITHTIME(v, ts, 'LONG'), "
           "FIRSTWITHTIME(v, ts, 'LONG') FROM t GROUP BY k ORDER BY k")
    r_host = _engine(segs, False).execute(sql)
    r_dev = _engine(segs, True).execute(sql)
    assert r_host["resultTable"]["rows"] == r_dev["resultTable"]["rows"]


def test_mesh_combine(segments):
    """8-way CPU mesh shard + pmin/pmax-pair combine == single device ==
    host (the combine family VERDICT r4 flagged as missing)."""
    from pinot_tpu.engine.device import DeviceExecutor
    from pinot_tpu.parallel.mesh import make_mesh

    _, segs = segments
    sql = ("SELECT k, LASTWITHTIME(v, ts, 'LONG'), "
           "FIRSTWITHTIME(v, ts, 'LONG') FROM t GROUP BY k ORDER BY k")
    eng = QueryEngine(device_executor=DeviceExecutor(mesh=make_mesh(8),
                                                     mm_mode="interpret"))
    for s in segs:
        eng.add_segment("t", s)
    r_mesh = eng.execute(sql)
    assert not r_mesh.get("exceptions"), r_mesh
    r_host = _engine(segs, False).execute(sql)
    assert r_mesh["resultTable"]["rows"] == r_host["resultTable"]["rows"]


def test_string_values_host(tmp_path):
    """STRING dataType (host path: the device rejects non-numeric value
    columns and falls back)."""
    schema = Schema.build(
        name="s", dimensions=[("k", DataType.STRING),
                              ("who", DataType.STRING)],
        metrics=[("ts", DataType.LONG)])
    df = pd.DataFrame({
        "k": ["x", "x", "y", "y", "y"],
        "who": ["ann", "bob", "cat", "dan", "eve"],
        "ts": np.array([5, 9, 2, 7, 7], dtype=np.int64),
    })
    seg = build_segment(schema, {c: df[c].to_numpy() for c in df},
                        str(tmp_path / "s0"))
    eng = QueryEngine(device_executor=None)
    eng.add_segment("s", seg)
    r = eng.execute("SELECT k, LASTWITHTIME(who, ts, 'STRING') FROM s "
                    "GROUP BY k ORDER BY k")
    assert not r.get("exceptions"), r
    # x: latest ts=9 -> bob; y: tie at ts=7 -> max('dan','eve') = 'eve'
    assert r["resultTable"]["rows"] == [["x", "bob"], ["y", "eve"]]
    r2 = eng.execute("SELECT FIRSTWITHTIME(who, ts, 'STRING') FROM s")
    assert r2["resultTable"]["rows"][0][0] == "cat"


def test_partial_wire_roundtrip(segments):
    """Server partials (val,time states, incl. string values) survive the
    DataTable encode/decode."""
    from pinot_tpu.engine import aggspec
    from pinot_tpu.engine.host import HostExecutor
    from pinot_tpu.query.context import Expression

    _, segs = segments
    eng = _engine(segs, False)
    from pinot_tpu.sql.compiler import compile_query

    q = compile_query("SELECT k, LASTWITHTIME(v, ts, 'LONG') FROM t GROUP BY k")
    res = eng.execute_segments(q, list(eng.tables["t"].segments.values()))
    back = decode(encode(res))
    p0, p1 = res.agg_partials[0], back.agg_partials[0]
    np.testing.assert_array_equal(p0["time"], p1["time"])
    np.testing.assert_array_equal(p0["val"], p1["val"])
    # string-valued state round-trip (scalar_str wire kind)
    sval = np.empty(3, dtype=object)
    sval[:] = ["zed", None, "amy"]
    arr = {}
    meta = {}
    from pinot_tpu.engine.datatable import _flatten_obj, _unflatten_obj

    _flatten_obj("x", sval, arr, meta)
    out = _unflatten_obj("x", meta["x"], arr)
    assert list(out) == ["zed", None, "amy"]


def test_empty_groups_and_no_match(segments):
    _, segs = segments
    for device in (False, True):
        eng = _engine(segs, device)
        r = eng.execute("SELECT LASTWITHTIME(v, ts, 'LONG') FROM t "
                        "WHERE k = 'zzz_not_there'")
        assert not r.get("exceptions"), r
        val = r["resultTable"]["rows"][0][0]
        assert val is None or (isinstance(val, float) and np.isnan(val)) \
            or val == "null", val


def test_long_beyond_2p53_exact_on_host(tmp_path):
    """ADVICE r5: LONG values with |v| > 2^53 must survive the host path
    EXACTLY (the old float64 state rounded them); the winning value, its
    wire round trip, and the broker merge all carry the native long. The
    device path's value plane stays float64 (documented in PARITY.md)."""
    schema = Schema.build(
        name="big", dimensions=[("k", DataType.STRING)],
        metrics=[("v", DataType.LONG), ("ts", DataType.LONG)])
    base = (1 << 53) + 1  # first integer float64 cannot represent
    df = pd.DataFrame({
        "k": ["a", "a", "a", "b", "b"],
        "v": np.array([base, base + 2, 7, -base - 4, 11], dtype=np.int64),
        "ts": np.array([5, 9, 1, 3, 2], dtype=np.int64),
    })
    segs = [build_segment(
        schema, {c: df.iloc[i::2][c].to_numpy() for c in df},
        str(tmp_path / f"s{i}"), segment_name=f"s{i}") for i in range(2)]
    eng = QueryEngine(device_executor=None)
    for s in segs:
        eng.add_segment("big", s)
    r = eng.execute("SELECT k, LASTWITHTIME(v, ts, 'LONG'), "
                    "FIRSTWITHTIME(v, ts, 'LONG') FROM big "
                    "GROUP BY k ORDER BY k")
    assert not r.get("exceptions"), r
    # a: last ts=9 -> base+2 (float64 would render base+2 as base+2±1);
    #    first ts=1 -> 7. b: last ts=3 -> -base-4; first ts=2 -> 11.
    assert r["resultTable"]["rows"] == [
        ["a", base + 2, 7], ["b", -base - 4, 11]]
    # the multi-segment merge above already crossed scatter_merge; now the
    # wire: a server partial's exact int plane survives encode/decode
    from pinot_tpu.sql.compiler import compile_query

    q = compile_query("SELECT k, LASTWITHTIME(v, ts, 'LONG') FROM big "
                      "GROUP BY k")
    res = eng.execute_segments(q, list(eng.tables["big"].segments.values()))
    back = decode(encode(res))
    assert list(back.agg_partials[0]["val"]) == list(res.agg_partials[0]["val"])
    assert (base + 2) in list(back.agg_partials[0]["val"])


def test_mixed_host_device_partial_wire_roundtrip(tmp_path):
    """A server hosting BOTH device-eligible and host-path segments merges
    a device float64 FirstLast partial into the host exact-int object
    accumulator; the mixed plane must survive the DataTable wire (typed
    exact_scalar flags) and render correctly end to end."""
    schema = Schema.build(
        name="mx", dimensions=[("k", DataType.STRING)],
        metrics=[("v", DataType.LONG), ("ts", DataType.LONG)])
    df = pd.DataFrame({
        "k": ["a", "a", "b", "b"],
        "v": np.array([3, 9, 20, 11], dtype=np.int64),
        "ts": np.array([1, 6, 2, 8], dtype=np.int64),
    })
    dev_seg = build_segment(schema, {c: df.iloc[:2][c].to_numpy() for c in df},
                            str(tmp_path / "dev"), segment_name="dev")
    host_seg = build_segment(schema, {c: df.iloc[2:][c].to_numpy() for c in df},
                             str(tmp_path / "host"), segment_name="host")
    # an upsert-style validDocIds mask forces the host scan path
    host_seg.valid_docs_mask = np.ones(host_seg.n_docs, dtype=bool)
    eng = QueryEngine()
    eng.add_segment("mx", dev_seg)
    eng.add_segment("mx", host_seg)
    from pinot_tpu.sql.compiler import compile_query

    q = compile_query("SELECT k, LASTWITHTIME(v, ts, 'LONG') FROM mx "
                      "GROUP BY k")
    res = eng.execute_segments(q, list(eng.tables["mx"].segments.values()))
    back = decode(encode(res))  # must not raise, must not drift types
    assert [float(x) for x in back.agg_partials[0]["val"]] == \
        [float(x) for x in res.agg_partials[0]["val"]]
    r = eng.execute("SELECT k, LASTWITHTIME(v, ts, 'LONG') FROM mx "
                    "GROUP BY k ORDER BY k")
    assert not r.get("exceptions"), r
    assert r["resultTable"]["rows"] == [["a", 9], ["b", 11]]


def test_nan_values_lose_ties(tmp_path):
    """NaN values never win the tie-break on ANY backend (review finding:
    XLA max propagates NaN; the kernels mask it out)."""
    schema = Schema.build(
        name="n", dimensions=[("k", DataType.STRING)],
        metrics=[("v", DataType.DOUBLE), ("ts", DataType.LONG)])
    df = pd.DataFrame({
        "k": ["a", "a", "a", "b"],
        "v": [np.nan, 5.0, 1.0, np.nan],
        "ts": np.array([7, 7, 3, 9], dtype=np.int64),
    })
    seg_dir = str(tmp_path / "s0")
    seg = build_segment(schema, {c: df[c].to_numpy() for c in df}, seg_dir)
    sql = ("SELECT k, LASTWITHTIME(v, ts, 'DOUBLE') FROM n "
           "GROUP BY k ORDER BY k")
    rows = {}
    for device in (False, True):
        eng = QueryEngine(device_executor="auto" if device else None)
        eng.add_segment("n", seg)
        r = eng.execute(sql)
        assert not r.get("exceptions"), r
        rows[device] = r["resultTable"]["rows"]
    # a: ts tie at 7, NaN loses -> 5.0; b: only value is NaN -> NaN
    # (group-by rows keep NaN like every other aggregation over NaN data)
    assert rows[False][0] == ["a", 5.0]
    assert rows[False][1][0] == "b"
    bval = rows[False][1][1]
    assert bval is None or (isinstance(bval, float) and np.isnan(bval))
    assert rows[False][0] == rows[True][0]
    assert str(rows[False][1]) == str(rows[True][1])  # NaN != NaN
