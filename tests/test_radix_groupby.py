"""Radix-partitioned group-by primitive tests (ops/radix_groupby.py).

The chunked-sort basis must be EXACTLY equivalent to a numpy group-by
oracle for every partial it emits — the device regime (engine/device.py
groupby_sorted) and the mesh combine (parallel/mesh.py) both build on
these invariants:

- pack_keys narrows to int32 exactly when the cartesian key space fits;
- chunked_group_aggregate's table matches the oracle for COUNT / int SUM /
  float SUM / MIN / MAX through single-chunk, multi-chunk and multi-LEVEL
  merge plans (chunk_rows forces the plans the 100M-row shapes take);
- overflow (distinct > K) is always DETECTED (n_groups_total > K), never
  silently truncated;
- merge_tables re-merges per-shard tables by key with neutral empty fills;
- hll_chunked_sorted_keys preserves the per-slot max-rho structure of the
  monolithic sort it replaces;
- bucket_histogram matches np.bincount over the radix partition.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pinot_tpu.ops import radix_groupby as radix


def _oracle(keys, vals=None):
    """numpy group-by: {key: (count, sum, min, max)} over real keys."""
    out = {}
    for i, k in enumerate(keys):
        v = None if vals is None else vals[i]
        c, s, lo, hi = out.get(k, (0, 0, None, None))
        if v is None:
            out[k] = (c + 1, 0, None, None)
        else:
            out[k] = (c + 1, s + v,
                      v if lo is None else min(lo, v),
                      v if hi is None else max(hi, v))
    return out


class TestPackKeys:
    def test_int32_when_space_fits(self):
        g = [jnp.array([0, 3, 1]), jnp.array([2, 0, 1])]
        key = radix.pack_keys(g, (4, 3), jnp.array([True, True, True]))
        assert key.dtype == jnp.int32
        assert key.tolist() == [0 * 3 + 2, 3 * 3 + 0, 1 * 3 + 1]

    def test_int64_fallback_for_wide_spaces(self):
        g = [jnp.array([1]), jnp.array([1])]
        cards = (1 << 16, 1 << 16)  # product 2^32 >= 2^31
        key = radix.pack_keys(g, cards, jnp.array([True]))
        assert key.dtype == jnp.int64
        assert key.tolist() == [(1 << 16) + 1]

    def test_masked_rows_get_sentinel(self):
        g = [jnp.array([0, 1])]
        key = radix.pack_keys(g, (8,), jnp.array([True, False]))
        assert key.tolist() == [0, radix.INT32_SENTINEL]


class TestPlanChunks:
    def test_small_n_degenerates_to_single_chunk(self):
        assert radix.plan_chunks(10_000, 1000) == (1, 10_000)

    def test_chunking_engages_when_compaction_pays(self):
        C, L = radix.plan_chunks(64 << 20, 1000, chunk_rows=1 << 20)
        assert C == 64 and L == 1 << 20

    def test_wide_k_grows_chunks_then_gives_up(self):
        # K so large no compaction ratio is reachable: monolithic plan
        C, L = radix.plan_chunks(4 << 20, 16 << 20, chunk_rows=1 << 20)
        assert C == 1


def _run_agg(keys, payloads, sums, mins, maxs, K, chunk_rows=None):
    fn = jax.jit(lambda k, p: radix.chunked_group_aggregate(
        k, {n: (p[n], kind) for n, (_, kind) in payloads.items()},
        sums, mins, maxs, K, chunk_rows=chunk_rows))
    return fn(keys, {n: v for n, (v, _) in payloads.items()})


class TestChunkedGroupAggregate:
    # chunk_rows=None: single monolithic chunk. 256: multi-chunk, one
    # merge level. 64: forces MULTI-LEVEL merges at n=2000 (levels of
    # compacted partials re-enter the chunked structure).
    @pytest.mark.parametrize("chunk_rows", [None, 256, 64])
    def test_matches_oracle_all_families(self, chunk_rows):
        rng = np.random.default_rng(7)
        n, nkeys, K = 2000, 40, 50
        keys = rng.integers(0, nkeys, n).astype(np.int32)
        ivals = rng.integers(-500, 500, n).astype(np.int64)
        fvals = rng.uniform(-10, 10, n)
        mask = rng.random(n) < 0.9
        kj = jnp.where(jnp.asarray(mask), jnp.asarray(keys),
                       radix.INT32_SENTINEL)
        tbl = _run_agg(
            kj,
            {"pi": (jnp.asarray(ivals), "int"),
             "pf": (jnp.asarray(fvals), "float")},
            {"pi", "pf"}, {"pi"}, {"pf"}, K, chunk_rows)
        want = _orc = {}
        for k, iv, fv, m in zip(keys, ivals, fvals, mask):
            if not m:
                continue
            c, si, sf, lo, hi = want.get(k, (0, 0, 0.0, None, None))
            want[k] = (c + 1, si + iv, sf + fv,
                       iv if lo is None else min(lo, iv),
                       fv if hi is None else max(hi, fv))
        total = int(tbl["n_groups_total"])
        assert total == len(want)
        got = {}
        sk = np.asarray(tbl["skeys"])
        for j in range(len(sk)):
            if bool(tbl["empty"][j]):
                continue
            got[int(sk[j])] = (
                int(tbl["gcount"][j]), int(tbl["sum::pi"][j]),
                float(tbl["sum::pf"][j]), int(tbl["min::pi"][j]),
                float(tbl["max::pf"][j]))
        assert set(got) == set(want)
        for k, (c, si, sf, lo, hi) in want.items():
            gc, gsi, gsf, glo, ghi = got[k]
            assert (gc, gsi, glo) == (c, si, lo), k
            assert gsf == pytest.approx(sf, rel=1e-12)
            assert ghi == hi, k

    @pytest.mark.parametrize("chunk_rows", [None, 256])
    def test_overflow_detected_never_truncated_silently(self, chunk_rows):
        rng = np.random.default_rng(8)
        n, K = 3000, 100
        keys = jnp.asarray(rng.permutation(n).astype(np.int32))  # all unique
        tbl = _run_agg(keys, {}, set(), set(), set(), K, chunk_rows)
        # distinct(3000) > K(100): the executor's host-fallback contract
        # is n_groups_total > K, regardless of which level detected it
        assert int(tbl["n_groups_total"]) > K

    def test_exact_int_sums_under_wrapping_cumsum(self):
        # the int path takes cumsum differences; huge values exercise the
        # two's-complement wrap argument
        big = (1 << 62) - 7
        keys = jnp.array([0, 1, 0, 1], dtype=jnp.int32)
        vals = jnp.array([big, -big, big, -big], dtype=jnp.int64)
        tbl = _run_agg(keys, {"p": (vals, "int")}, {"p"}, set(), set(), 8)
        s = np.asarray(tbl["sum::p"])
        sk = np.asarray(tbl["skeys"])
        got = {int(k): int(v) for k, v in zip(sk[:2], s[:2])}
        # 2*big wraps int64 transiently; the group sums recover exactly
        # under two's-complement arithmetic (matches the host path's
        # int64 accumulation)
        assert got[0] == np.int64(big * 2)
        assert got[1] == np.int64(-big * 2)


class TestMergeTables:
    def test_cross_shard_key_aligned_merge(self):
        SEN = radix.INT64_SENTINEL
        sk = jnp.array([[2, 5, 9, SEN], [5, 9, 30, SEN]], dtype=jnp.int64)
        cnt = jnp.array([[2, 1, 3, 0], [4, 1, 1, 0]], dtype=jnp.int64)
        mn = jnp.array([[1, 7, 2, 2**62], [3, 1, 8, 2**62]], dtype=jnp.int64)
        cols, fk, empty, dist = radix.merge_tables(
            sk, {"gcount": cnt, "m": mn},
            {"gcount": "sum", "m": "min"}, 8)
        assert int(dist) == 4
        got = {int(k): (int(c), int(m)) for k, c, m, e in zip(
            fk, cols["gcount"], cols["m"], empty) if not bool(e)}
        assert got == {2: (2, 1), 5: (5, 3), 9: (4, 1), 30: (1, 8)}

    def test_empty_slots_carry_neutral_fills(self):
        """Non-run-end entries land in the sentinel region of the final
        sort carrying PARTIAL scan values — they must come out re-filled
        with neutrals or the executor would see phantom groups (the mesh
        combine reads gcount > 0)."""
        SEN = radix.INT64_SENTINEL
        sk = jnp.array([[7, SEN], [7, SEN]], dtype=jnp.int64)
        cnt = jnp.array([[3, 0], [2, 0]], dtype=jnp.int64)
        cols, fk, empty, dist = radix.merge_tables(
            sk, {"gcount": cnt}, {"gcount": "sum"}, 4)
        assert int(dist) == 1
        assert np.asarray(cols["gcount"])[np.asarray(empty)].max(
            initial=0) == 0


class TestHllChunkedSortedKeys:
    @pytest.mark.parametrize("chunk_rows", [None, 128])
    def test_slot_max_structure_preserved(self, chunk_rows):
        rng = np.random.default_rng(9)
        n, n_slots = 5000, 300
        slot = rng.integers(0, n_slots, n).astype(np.int32)
        rho = rng.integers(1, 23, n).astype(np.int32)
        packed = jnp.asarray((slot << 5) | rho)
        out = np.asarray(jax.jit(
            lambda p: radix.hll_chunked_sorted_keys(
                p, n_slots, chunk_rows=chunk_rows))(packed))
        # drop pad sentinels, read per-slot max rho at slot-run ends
        out = out[out != radix.INT32_SENTINEL]
        assert np.all(np.diff(out) >= 0)  # globally sorted (drop-in operand)
        got = {}
        for v in out.tolist():
            got[v >> 5] = v & 31  # ascending: last write per slot = max
        want = {}
        for s, r in zip(slot.tolist(), rho.tolist()):
            want[s] = max(want.get(s, 0), r)
        assert got == want


class TestBucketHistogram:
    def test_matches_bincount(self):
        rng = np.random.default_rng(10)
        n, keyspace, n_buckets = 4096, 5000, 16
        keys = rng.integers(0, keyspace, n).astype(np.int32)
        mask = rng.random(n) < 0.8
        kj = jnp.where(jnp.asarray(mask), jnp.asarray(keys),
                       radix.INT32_SENTINEL)
        counts = np.asarray(radix.bucket_histogram(
            kj, keyspace, n_buckets, interpret=True))
        shift = 0
        while (keyspace - 1) >> shift >= n_buckets:
            shift += 1
        want = np.bincount(keys[mask] >> shift, minlength=n_buckets)
        assert counts.tolist() == want.tolist()
