"""Plan-advisor memo lifecycle (ISSUE 17).

The contract under test: per-template plan memos are LRU-bounded under
template churn (evictions counted, most-recent survive), advice decays
toward the static defaults when a template's measurements drift (the
drift cooldown stands every decision down until the signal re-converges),
``SET useAdvisor=false`` has ZERO memo effect (no reads, no writes,
bit-exact results against advisor-on), confirming decisions never stamp
an ``ADVISOR(...)`` line, and memo updates are thread-safe under the
PR-2 concurrent-launch path.
"""

import threading

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine.advisor import PlanAdvisor, advisor_enabled
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment

# ---------------------------------------------------------------------------
# unit: memo store
# ---------------------------------------------------------------------------


def test_lru_eviction_under_template_churn():
    adv = PlanAdvisor(max_memos=4, min_samples=2)
    for i in range(10):
        adv.observe(f"tpl{i}", skip_ratio=0.5)
    assert len(adv) == 4
    assert adv.evictions == 6
    # the most recently observed templates survive; the churned-out
    # oldest are gone
    assert adv.peek("tpl9") is not None
    assert adv.peek("tpl0") is None
    # touching a survivor protects it from the next eviction wave
    adv.observe("tpl6", skip_ratio=0.5)
    adv.observe("tplA", skip_ratio=0.5)
    assert adv.peek("tpl6") is not None
    assert adv.peek("tpl7") is None


def test_advice_needs_min_samples():
    adv = PlanAdvisor(min_samples=3)
    adv.observe("t", skip_ratio=0.9)
    adv.observe("t", skip_ratio=0.9)
    frac, note = adv.advise_blockskip("t", 16)
    assert (frac, note) == (16, None)  # still cold: default, no stamp
    assert adv.convergence("t") == "cold"
    adv.observe("t", skip_ratio=0.9)
    frac, note = adv.advise_blockskip("t", 16)
    assert frac == 0 and "ADVISOR(blockSkip=dense" in note
    assert adv.convergence("t") == "converged"


def test_confirming_decision_does_not_stamp():
    adv = PlanAdvisor(min_samples=2)
    for _ in range(3):
        adv.observe("t", build_rows={"d": 100})
    # measured 100 <= threshold confirms the BROADCAST default
    strat, note = adv.advise_join_strategy("t", "BROADCAST", "d", 1000)
    assert (strat, note) == ("BROADCAST", None)
    assert adv.peek("t").decisions == 1
    assert adv.peek("t").overrides == 0
    # ...and overrides it once the measurement says otherwise
    for _ in range(4):
        adv.observe("t", build_rows={"d": 50_000})
    strat, note = adv.advise_join_strategy("t", "BROADCAST", "d", 1000)
    assert strat == "SHUFFLE" and "ADVISOR(joinStrategy=SHUFFLE" in note


def test_drift_decays_advice_toward_default():
    adv = PlanAdvisor(min_samples=3)
    for _ in range(4):
        adv.observe("t", skip_ratio=0.01)
    frac, note = adv.advise_blockskip("t", 16)
    # 0.01 * CAND_HEADROOM fits under 1/32 but not 1/64
    assert frac == 32 and "ADVISOR(candBound=1/32" in note
    # the table's shape drifts: selectivity jumps past the drift factor
    adv.observe("t", skip_ratio=1.0)
    assert adv.convergence("t") == "drifting"
    frac, note = adv.advise_blockskip("t", 16)
    assert (frac, note) == (16, None)  # advice stands down to the default
    # consistent re-measurement re-converges and advice resumes — now
    # reflecting the NEW reality (non-selective => dense)
    for _ in range(8):
        adv.observe("t", skip_ratio=1.0)
    assert adv.convergence("t") == "converged"
    frac, note = adv.advise_blockskip("t", 16)
    assert frac == 0 and "blockSkip=dense" in note


def test_trim_advice_no_drop_rule():
    adv = PlanAdvisor(min_samples=2)
    for _ in range(3):
        adv.observe("t", groups=900)
    trim, note = adv.advise_trim("t", 5000)
    # pow2 >= 900 * 1.5 headroom: tightened but never below the observed
    # high-water group count
    assert trim == 2048 and "ADVISOR(groupTrim=2048" in note
    # an overflow observation (advised keep < actual groups) resets the
    # signal: advice stands down
    adv.observe("t", groups=4000, trim_keep=2048)
    assert adv.peek("t").trim_overflows == 1
    trim, note = adv.advise_trim("t", 5000)
    assert (trim, note) == (5000, None)


def test_dense_blockskip_advice_reprobes():
    adv = PlanAdvisor(min_samples=2, reprobe_every=4)
    for _ in range(3):
        adv.observe("t", skip_ratio=1.0)
    picks = [adv.advise_blockskip("t", 16)[0] for _ in range(8)]
    # mostly dense, but every reprobe_every-th decision returns the
    # default so the (skip-path-only) ratio stays measurable
    assert 16 in picks and picks.count(0) >= 5


def test_observe_thread_safety():
    adv = PlanAdvisor(max_memos=8, min_samples=2)
    n_threads, n_iter = 8, 300
    errors = []

    def work(t):
        try:
            for i in range(n_iter):
                key = f"tpl{(t + i) % 12}"
                adv.observe(key, skip_ratio=0.3, groups=50 + i % 7,
                            cohort=1 + i % 3,
                            build_rows={"d": 1000 + i})
                adv.advise_blockskip(key, 16)
                adv.advise_trim(key, 5000)
                adv.snapshot()
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=work, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors
    assert adv.observations == n_threads * n_iter
    assert len(adv) <= 8


def test_advisor_enabled_option_parsing():
    assert advisor_enabled({}) is True
    assert advisor_enabled({"useadvisor": "false"}) is False
    assert advisor_enabled({"useadvisor": "'false'"}) is False  # quoted
    assert advisor_enabled({"useadvisor": "true"}) is True
    assert PlanAdvisor.from_config() is not None


# ---------------------------------------------------------------------------
# integration: the engine loop
# ---------------------------------------------------------------------------

ROWS = 8_192  # ZONE_BLOCK_ROWS-aligned: block-skip eligible


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    rng = np.random.default_rng(61)
    schema = Schema.build(
        name="adv",
        dimensions=[("ts", DataType.LONG)],
        metrics=[("m", DataType.INT)])
    cfg = TableConfig(
        table_name="adv",
        indexing=IndexingConfig(no_dictionary_columns=["ts"]))
    base = tmp_path_factory.mktemp("advisor")
    out = []
    for i in range(2):
        build_segment(
            schema,
            {"ts": (np.int64(i) * ROWS
                    + np.arange(ROWS, dtype=np.int64)),
             "m": rng.integers(0, 100, ROWS).astype(np.int32)},
            str(base / f"s{i}"), cfg, f"s{i}")
        out.append(ImmutableSegment(str(base / f"s{i}")))
    return out


@pytest.fixture(scope="module")
def engine(segs):
    eng = QueryEngine()
    for s in segs:
        eng.add_segment("adv", s)
    return eng


def _sql(i):
    # non-selective zone-prunable range: every block matches, so the
    # advisor learns ratio 1.0 and advises the dense form
    return (f"SET usePartialsCache = false; "
            f"SELECT COUNT(*), SUM(m) FROM adv "
            f"WHERE ts BETWEEN 0 AND {10 * 2 * ROWS + i}")


def test_use_advisor_false_zero_memo_effect_and_bit_exact(engine):
    advisor = engine.device.advisor
    assert advisor is not None and len(advisor) == 0
    # advisor-off queries: no reads, NO writes — the memo store stays
    # empty no matter how many run
    off_rows = None
    for i in range(4):
        r = engine.execute(f"SET useAdvisor = false; {_sql(i)}")
        assert not r["exceptions"]
        assert "advisorDecisions" not in r
        off_rows = r["resultTable"]["rows"]
    assert len(advisor) == 0
    # advisor-on training converges to the dense override...
    stamped_at = None
    for i in range(8):
        r = engine.execute(_sql(i))
        assert not r["exceptions"]
        assert r["resultTable"]["rows"] == off_rows  # bit-exact throughout
        if stamped_at is None and any(
                "ADVISOR(blockSkip=dense" in line
                for line in r.get("advisorDecisions") or ()):
            stamped_at = i
            break
    assert stamped_at is not None, "advisor never converged"
    assert len(advisor) == 1
    # ...and the advised (dense) execution stays bit-exact against a
    # fresh advisor-off twin
    twin = engine.execute(f"SET useAdvisor = false; {_sql(0)}")
    assert engine.execute(_sql(0))["resultTable"]["rows"] \
        == twin["resultTable"]["rows"]


def test_memo_updates_safe_under_concurrent_launches(engine):
    errors = []
    results = []
    barrier = threading.Barrier(6)

    def work(t):
        try:
            barrier.wait(timeout=30)
            for i in range(4):
                r = engine.execute(_sql(100 + t * 10 + i))
                assert not r["exceptions"]
                results.append(r["resultTable"]["rows"])
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=work, args=(t,)) for t in range(6)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors
    # every concurrent launch computed the same (full-table) answer
    assert len({tuple(map(tuple, rows)) for rows in results}) == 1
    memo = engine.device.advisor.peek(
        next(iter(engine.device.advisor.snapshot()["templates"])))
    assert memo is not None and memo.executions > 0
