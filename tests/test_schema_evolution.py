"""Additive schema evolution: new columns over pre-existing segments.

Reference analogs: Schema REST update + SchemaUtils backward-compat
validation + segment reload synthesizing default null values for columns
a segment predates.
"""

import time

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment


def wait_until(cond, timeout=15.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.05)
    return False


def _v1_schema():
    return Schema.build(name="emps",
                        dimensions=[("name", DataType.STRING)],
                        metrics=[("salary", DataType.LONG)])


def _v2_schema():
    return Schema.build(name="emps",
                        dimensions=[("name", DataType.STRING),
                                    ("region", DataType.STRING)],
                        metrics=[("salary", DataType.LONG),
                                 ("bonus", DataType.LONG)])


class TestSchemaEvolution:
    def test_add_columns_defaults_over_old_segments(self, tmp_path):
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        server = ServerInstance("s0", registry, str(tmp_path / "sd"),
                                device_executor=None)
        server.start()
        broker = Broker(registry, timeout_s=10.0)
        try:
            cfg = TableConfig(table_name="emps")
            controller.add_table(cfg, _v1_schema())
            build_segment(_v1_schema(),
                          {"name": np.array(["ann", "bob"]),
                           "salary": np.array([100, 200], dtype=np.int64)},
                          str(tmp_path / "u0"), cfg, "old0")
            controller.upload_segment("emps", str(tmp_path / "u0"))
            assert wait_until(
                lambda: len(registry.external_view("emps_OFFLINE")) == 1)

            controller.update_schema("emps", _v2_schema())
            # a new segment built WITH the evolved columns
            build_segment(_v2_schema(),
                          {"name": np.array(["cat"]),
                           "region": np.array(["emea"]),
                           "salary": np.array([300], dtype=np.int64),
                           "bonus": np.array([30], dtype=np.int64)},
                          str(tmp_path / "u1"), cfg, "new0")
            controller.upload_segment("emps", str(tmp_path / "u1"))
            assert wait_until(
                lambda: len(registry.external_view("emps_OFFLINE")) == 2)
            # old segment must have picked up the evolved schema
            assert wait_until(lambda: all(
                getattr(s, "table_schema", None) is not None
                and "bonus" in s.table_schema.fields
                for s in server.engine.tables["emps_OFFLINE"].segments.values()))

            r = broker.execute(
                "SELECT name, region, salary, bonus FROM emps ORDER BY name")
            assert not r.get("exceptions"), r
            # dimension default null is "null", metric default is 0 (reference
            # FieldSpec defaults)
            assert r["resultTable"]["rows"] == [
                ["ann", "null", 100, 0], ["bob", "null", 200, 0],
                ["cat", "emea", 300, 30]]

            r = broker.execute("SELECT SUM(bonus), COUNT(*) FROM emps")
            assert r["resultTable"]["rows"] == [[30, 3]]

            # old-segment rows are NULL for the evolved column
            r = broker.execute(
                "SELECT COUNT(*) FROM emps WHERE region IS NULL")
            assert r["resultTable"]["rows"][0][0] == 2
            r = broker.execute(
                "SELECT region, SUM(salary) FROM emps GROUP BY region "
                "ORDER BY region")
            assert r["resultTable"]["rows"] == [["emea", 300], ["null", 300]]
        finally:
            broker.close()
            server.stop()

    def test_evolved_mv_column_and_unknown_column(self, tmp_path):
        """Evolved MV columns have zero entries per doc (predicates match
        nothing, MV aggs see no entries); a column in NEITHER segment nor
        schema errors instead of silently matching (r3 review)."""
        from pinot_tpu.engine.engine import QueryEngine

        eng = QueryEngine(device_executor=None)
        seg = build_segment(_v1_schema(),
                            {"name": np.array(["ann"]),
                             "salary": np.array([1], dtype=np.int64)},
                            str(tmp_path / "s"), TableConfig(table_name="emps"),
                            "s0")
        seg.table_schema = Schema.build(
            name="emps",
            dimensions=[("name", DataType.STRING)],
            metrics=[("salary", DataType.LONG)],
            multi_value_dimensions=[("tags", DataType.STRING)])
        eng.add_segment("emps", seg)
        r = eng.execute("SELECT COUNT(*) FROM emps WHERE tags = 'x'")
        assert r["resultTable"]["rows"] == [[0]]
        r = eng.execute("SELECT COUNTMV(tags) FROM emps")
        assert r["resultTable"]["rows"] == [[0]]
        r = eng.execute("SELECT COUNT(*) FROM emps WHERE tags IS NULL")
        assert r["resultTable"]["rows"] == [[1]]
        # unknown everywhere: error, not a silent zero/all match
        r = eng.execute("SELECT COUNT(*) FROM emps WHERE nope IS NOT NULL")
        assert r["exceptions"]

    def test_hybrid_evolution_updates_both_variants(self, tmp_path):
        from pinot_tpu.common.table_config import StreamConfig, TableType
        from pinot_tpu.stream.memory_stream import TopicRegistry

        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        server = ServerInstance("s0", registry, str(tmp_path / "sd"),
                                device_executor=None)
        server.start()
        try:
            TopicRegistry.delete("emps_evo")
            TopicRegistry.create("emps_evo", 1)
            controller.add_table(TableConfig(table_name="emps"), _v1_schema())
            controller.add_table(
                TableConfig(table_name="emps", table_type=TableType.REALTIME,
                            stream=StreamConfig(stream_type="memory",
                                                topic="emps_evo",
                                                decoder="json")),
                _v1_schema())
            controller.update_schema("emps", _v2_schema())
            assert "bonus" in registry.table_schema("emps_OFFLINE").fields
            assert "bonus" in registry.table_schema("emps_REALTIME").fields
        finally:
            server.stop()

    def test_rejects_drops_and_type_changes(self, tmp_path):
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        cfg = TableConfig(table_name="emps")
        controller.add_table(cfg, _v1_schema())
        with pytest.raises(ValueError, match="drop"):
            controller.update_schema(
                "emps", Schema.build(name="emps",
                                     metrics=[("salary", DataType.LONG)]))
        with pytest.raises(ValueError, match="type/shape"):
            controller.update_schema(
                "emps", Schema.build(name="emps",
                                     dimensions=[("name", DataType.STRING)],
                                     metrics=[("salary", DataType.DOUBLE)]))
        with pytest.raises(KeyError):
            controller.update_schema("nope", _v1_schema())
