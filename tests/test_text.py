"""Text index + TEXT_MATCH (Lucene analog).

Reference analogs: LuceneTextIndexReader/Creator, TextMatchFilterOperator
(pinot-core text_match tests) — terms, AND/OR, phrases, prefix wildcard.
"""

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.textindex import parse_text_query, tokenize_text

REVIEWS = [
    "Distributed query processing at scale",          # 0
    "Query planning and optimization for OLAP",       # 1
    "The quick brown fox jumps over the lazy dog",    # 2
    "Real-time stream processing with exactly-once",  # 3
    "Scale-out storage; query-processing pipelines",  # 4
]


@pytest.fixture(scope="module", params=[True, False], ids=["indexed", "scan"])
def engine(request, tmp_path_factory):
    tmp = tmp_path_factory.mktemp("text")
    schema = Schema.build(
        name="docs",
        dimensions=[("body", DataType.STRING), ("id", DataType.INT)],
    )
    cfg = TableConfig(
        table_name="docs",
        indexing=IndexingConfig(
            text_index_columns=["body"] if request.param else []),
    )
    eng = QueryEngine(device_executor=None)
    seg = build_segment(
        schema,
        {"body": np.asarray(REVIEWS, dtype=np.str_),
         "id": np.arange(len(REVIEWS), dtype=np.int32)},
        str(tmp / "seg"), cfg, "s0")
    eng.add_segment("docs", seg)
    return eng


def ids(eng, query):
    r = eng.execute(
        f"SELECT id FROM docs WHERE TEXT_MATCH(body, '{query}') ORDER BY id")
    assert not r.get("exceptions"), r
    return [row[0] for row in r["resultTable"]["rows"]]


class TestTokenize:
    def test_lowercase_alnum(self):
        assert tokenize_text("Real-time STREAM, processing!") == \
            ["real", "time", "stream", "processing"]


class TestParseQuery:
    def test_precedence(self):
        # AND binds tighter than OR
        assert parse_text_query("a b AND c") == \
            ("or", [("term", "a"), ("and", [("term", "b"), ("term", "c")])])

    def test_phrase_and_prefix(self):
        assert parse_text_query('"exactly once" AND stream*') == \
            ("and", [("phrase", "exactly once"), ("prefix", "stream")])

    def test_bad_query_raises(self):
        with pytest.raises(ValueError):
            parse_text_query("")


class TestTextMatch:
    def test_single_term(self, engine):
        assert ids(engine, "query") == [0, 1, 4]

    def test_case_insensitive(self, engine):
        assert ids(engine, "QUERY") == [0, 1, 4]

    def test_and(self, engine):
        assert ids(engine, "query AND processing") == [0, 4]

    def test_or_explicit_and_default(self, engine):
        assert ids(engine, "fox OR olap") == [1, 2]
        assert ids(engine, "fox olap") == [1, 2]  # Lucene default op

    def test_phrase(self, engine):
        assert ids(engine, '"query processing"') == [0, 4]
        assert ids(engine, '"processing query"') == []

    def test_prefix_wildcard(self, engine):
        assert ids(engine, "optim*") == [1]
        assert ids(engine, "pro*") == [0, 3, 4]

    def test_grouping(self, engine):
        assert ids(engine, "(fox OR olap) AND query") == [1]

    def test_no_match(self, engine):
        assert ids(engine, "zebra") == []

    def test_lowercase_and_is_a_term(self, engine):
        # operators are case-sensitive like Lucene: 'and' is a search term
        assert ids(engine, "planning and") == [1]  # doc 1 has both words
        assert parse_text_query("rock and roll") == \
            ("or", [("term", "rock"), ("term", "and"), ("term", "roll")])

    def test_explain_operator(self, engine):
        r = engine.execute(
            "EXPLAIN PLAN FOR SELECT COUNT(*) FROM docs "
            "WHERE TEXT_MATCH(body, 'query')")
        ops = " ".join(row[0] for row in r["resultTable"]["rows"])
        assert "FILTER_TEXT_INDEX" in ops or "FILTER_FULL_SCAN" in ops


class TestTextIndexValidation:
    def test_requires_string_column(self, tmp_path):
        schema = Schema.build(name="t", dimensions=[("x", DataType.INT)])
        cfg = TableConfig(table_name="t",
                          indexing=IndexingConfig(text_index_columns=["x"]))
        with pytest.raises(ValueError, match="text index"):
            build_segment(schema, {"x": np.arange(3, dtype=np.int32)},
                          str(tmp_path / "s"), cfg, "s0")
