"""End-to-end query correctness vs a sqlite3 oracle.

The reference's strategy (SURVEY.md §4): build real segments, run the full
server+broker pipeline in-process, and cross-check results against an
embedded SQL engine (it uses H2; we use sqlite3 — duckdb is not in this image). Two segments exercise the
per-segment execute + merge + reduce path, like the inner/inter-segment
query suites (pinot-core/src/test/.../queries/BaseQueriesTest.java).
"""

import math

import sqlite3
import numpy as np
import pytest

from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    rng = np.random.default_rng(7)
    n = 6000
    players = np.array([f"player_{i:03d}" for i in range(150)])
    teams = np.array([f"team_{i}" for i in range(25)])
    cols = {
        "playerName": players[rng.integers(0, len(players), n)],
        "teamID": teams[rng.integers(0, len(teams), n)],
        "league": np.array(["AL", "NL"])[rng.integers(0, 2, n)],
        "yearID": rng.integers(1980, 2020, n).astype(np.int32),
        "runs": rng.integers(0, 150, n).astype(np.int32),
        "hits": rng.integers(0, 200, n).astype(np.int32),
        "homeRuns": rng.integers(0, 60, n).astype(np.int32),
        "salary": np.round(rng.uniform(1e4, 1e7, n), 2),
    }
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema

    schema = Schema.build(
        name="baseballStats",
        dimensions=[
            ("playerName", DataType.STRING),
            ("teamID", DataType.STRING),
            ("league", DataType.STRING),
            ("yearID", DataType.INT),
        ],
        metrics=[
            ("runs", DataType.INT),
            ("hits", DataType.INT),
            ("homeRuns", DataType.INT),
            ("salary", DataType.DOUBLE),
        ],
    )
    cfg = TableConfig(
        table_name="baseballStats",
        indexing=IndexingConfig(
            inverted_index_columns=["teamID", "league"],
            bloom_filter_columns=["playerName"],
        ),
    )
    base = tmp_path_factory.mktemp("qseg")
    engine = QueryEngine()
    half = n // 2
    for i, sl in enumerate([slice(0, half), slice(half, n)]):
        part = {k: v[sl] for k, v in cols.items()}
        seg = build_segment(schema, part, str(base / f"s{i}"), cfg, f"s{i}")
        if not isinstance(seg, ImmutableSegment):
            seg = ImmutableSegment(str(base / f"s{i}"))
        engine.add_segment("baseballStats", seg)

    con = sqlite3.connect(":memory:")
    try:
        con.execute("SELECT MOD(1, 1)")
    except sqlite3.OperationalError:
        # sqlite < 3.35 has no built-in math functions; the oracle only
        # needs MOD
        con.create_function("MOD", 2, lambda a, b: None if b in (0, None)
                            or a is None else a % b)
    con.execute(
        "CREATE TABLE baseballStats (playerName TEXT, teamID TEXT, "
        "league TEXT, yearID INT, runs INT, hits INT, homeRuns INT, salary REAL)"
    )
    con.executemany(
        "INSERT INTO baseballStats VALUES (?,?,?,?,?,?,?,?)",
        list(
            zip(
                cols["playerName"].tolist(),
                cols["teamID"].tolist(),
                cols["league"].tolist(),
                cols["yearID"].tolist(),
                cols["runs"].tolist(),
                cols["hits"].tolist(),
                cols["homeRuns"].tolist(),
                cols["salary"].tolist(),
            )
        ),
    )
    return engine, con


def _norm(v):
    if v is None:
        return None
    if isinstance(v, bool):
        return float(v)
    if isinstance(v, (int, float)):
        f = float(v)
        return None if math.isnan(f) else f
    return v


def _rows_equal(a, b):
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            va, vb = _norm(va), _norm(vb)
            if va is None or vb is None:
                if va is not vb and not (va is None and vb is None):
                    return False
            elif isinstance(va, float) and isinstance(vb, float):
                # DOUBLE columns live as f32 on device (accumulated in f64),
                # so device-path results carry ~1e-7 relative error
                if not math.isclose(va, vb, rel_tol=1e-6, abs_tol=1e-6):
                    return False
            elif va != vb:
                return False
    return True


def check(setup, sql, oracle_sql=None, unordered=False):
    engine, con = setup
    resp = engine.execute(sql)
    assert not resp.get("exceptions"), resp.get("exceptions")
    got = [tuple(r) for r in resp["resultTable"]["rows"]]
    want = con.execute(oracle_sql or sql).fetchall()
    if unordered:
        got = sorted((tuple(map(repr, map(_norm, r))) for r in got))
        want = sorted((tuple(map(repr, map(_norm, r))) for r in want))
        assert got == want, f"\ngot:  {got[:5]}\nwant: {want[:5]}"
    else:
        assert _rows_equal(got, want), f"\ngot:  {got[:5]}\nwant: {want[:5]}"
    return resp


class TestAggregation:
    def test_count_star(self, setup):
        check(setup, "SELECT COUNT(*) FROM baseballStats")

    def test_basic_aggs(self, setup):
        check(
            setup,
            "SELECT SUM(runs), MIN(runs), MAX(runs), AVG(salary) FROM baseballStats",
        )

    def test_filtered_agg(self, setup):
        check(
            setup,
            "SELECT SUM(runs) FROM baseballStats WHERE teamID = 'team_3' AND yearID > 2000",
        )

    def test_in_between_like(self, setup):
        check(
            setup,
            "SELECT COUNT(*), SUM(hits) FROM baseballStats WHERE "
            "teamID IN ('team_1','team_2','team_19') AND yearID BETWEEN 1990 AND 2005 "
            "AND playerName LIKE 'player_0%'",
        )

    def test_not_filters(self, setup):
        check(
            setup,
            "SELECT COUNT(*) FROM baseballStats WHERE league != 'AL' AND "
            "teamID NOT IN ('team_1','team_2') AND NOT yearID < 1995",
        )

    def test_or_filter(self, setup):
        check(
            setup,
            "SELECT COUNT(*) FROM baseballStats WHERE teamID = 'team_1' OR "
            "(runs > 100 AND league = 'NL')",
        )

    def test_expression_filter(self, setup):
        check(
            setup,
            "SELECT COUNT(*) FROM baseballStats WHERE runs + hits > 250",
        )

    def test_empty_result(self, setup):
        resp = check(
            setup,
            "SELECT COUNT(*), SUM(runs), MAX(runs) FROM baseballStats WHERE league = 'XX'",
        )
        assert resp["resultTable"]["rows"][0][0] == 0

    def test_post_aggregation(self, setup):
        check(
            setup,
            "SELECT SUM(runs) / COUNT(*), MAX(runs) - MIN(runs) FROM baseballStats",
            oracle_sql="SELECT CAST(SUM(runs) AS REAL) / COUNT(*), MAX(runs) - MIN(runs) FROM baseballStats",
        )

    def test_minmaxrange(self, setup):
        check(
            setup,
            "SELECT MINMAXRANGE(runs) FROM baseballStats",
            oracle_sql="SELECT MAX(runs) - MIN(runs) FROM baseballStats",
        )

    def test_distinctcount(self, setup):
        check(
            setup,
            "SELECT DISTINCTCOUNT(teamID), COUNT(DISTINCT playerName) FROM baseballStats",
            oracle_sql="SELECT COUNT(DISTINCT teamID), COUNT(DISTINCT playerName) FROM baseballStats",
        )

    def test_percentile(self, setup):
        # PERCENTILE is digest-backed (bounded mergeable state): assert the
        # estimate's RANK error, not value equality with the exact oracle
        engine, con = setup
        resp = engine.execute("SELECT PERCENTILE(runs, 50) FROM baseballStats")
        got = resp["resultTable"]["rows"][0][0]
        vals = np.array([r[0] for r in con.execute("SELECT runs FROM baseballStats").fetchall()])
        rank_lo = float((vals < got).mean())
        rank_hi = float((vals <= got).mean())
        assert rank_lo - 0.02 <= 0.5 <= rank_hi + 0.02, (got, rank_lo, rank_hi)


class TestGroupBy:
    def test_sum_group_by(self, setup):
        check(
            setup,
            "SELECT playerName, SUM(runs) FROM baseballStats GROUP BY playerName "
            "ORDER BY SUM(runs) DESC, playerName LIMIT 20",
        )

    def test_multi_group_by(self, setup):
        check(
            setup,
            "SELECT league, teamID, COUNT(*), AVG(salary) FROM baseballStats "
            "GROUP BY league, teamID ORDER BY league, teamID LIMIT 100",
        )

    def test_group_by_with_filter(self, setup):
        check(
            setup,
            "SELECT teamID, MAX(homeRuns) FROM baseballStats WHERE yearID >= 2000 "
            "GROUP BY teamID ORDER BY teamID LIMIT 50",
        )

    def test_having(self, setup):
        check(
            setup,
            "SELECT teamID, COUNT(*) FROM baseballStats GROUP BY teamID "
            "HAVING COUNT(*) > 230 ORDER BY COUNT(*) DESC, teamID LIMIT 30",
        )

    def test_group_by_expression(self, setup):
        check(
            setup,
            "SELECT yearID - MOD(yearID, 10), SUM(runs) FROM baseballStats "
            "GROUP BY yearID - MOD(yearID, 10) ORDER BY 1 LIMIT 10",
            oracle_sql="SELECT yearID - MOD(yearID, 10) AS d, SUM(runs) FROM baseballStats "
            "GROUP BY d ORDER BY d LIMIT 10",
        )

    def test_post_agg_in_group_by(self, setup):
        check(
            setup,
            "SELECT league, SUM(runs) / SUM(hits) FROM baseballStats "
            "GROUP BY league ORDER BY league",
            oracle_sql="SELECT league, CAST(SUM(runs) AS REAL) / SUM(hits) FROM baseballStats "
            "GROUP BY league ORDER BY league",
        )

    def test_group_by_unordered(self, setup):
        check(
            setup,
            "SELECT teamID, SUM(runs) FROM baseballStats GROUP BY teamID LIMIT 1000",
            unordered=True,
        )

    def test_count_distinct_group_by(self, setup):
        check(
            setup,
            "SELECT league, DISTINCTCOUNT(playerName) FROM baseballStats "
            "GROUP BY league ORDER BY league",
            oracle_sql="SELECT league, COUNT(DISTINCT playerName) FROM baseballStats "
            "GROUP BY league ORDER BY league",
        )


class TestSelection:
    def test_selection_order_by(self, setup):
        check(
            setup,
            "SELECT playerName, runs FROM baseballStats "
            "ORDER BY runs DESC, playerName LIMIT 15",
        )

    def test_selection_filter_order(self, setup):
        check(
            setup,
            "SELECT playerName, teamID, salary FROM baseballStats WHERE league = 'AL' "
            "ORDER BY salary DESC, playerName, teamID LIMIT 10",
        )

    def test_selection_expression(self, setup):
        check(
            setup,
            "SELECT playerName, runs + hits FROM baseballStats "
            "ORDER BY runs + hits DESC, playerName LIMIT 12",
        )

    def test_selection_offset(self, setup):
        check(
            setup,
            "SELECT playerName, runs FROM baseballStats "
            "ORDER BY runs DESC, playerName LIMIT 10 OFFSET 20",
        )

    def test_selection_no_order(self, setup):
        engine, con = setup
        resp = engine.execute("SELECT playerName FROM baseballStats LIMIT 7")
        assert len(resp["resultTable"]["rows"]) == 7

    def test_case_expression(self, setup):
        check(
            setup,
            "SELECT playerName, CASE WHEN runs > 100 THEN 'high' ELSE 'low' END "
            "FROM baseballStats ORDER BY runs DESC, playerName LIMIT 8",
            oracle_sql="SELECT playerName, CASE WHEN runs > 100 THEN 'high' ELSE 'low' END "
            "FROM baseballStats ORDER BY runs DESC, playerName LIMIT 8",
        )


class TestDistinct:
    def test_distinct(self, setup):
        check(
            setup,
            "SELECT DISTINCT league FROM baseballStats ORDER BY league",
        )

    def test_distinct_multi(self, setup):
        check(
            setup,
            "SELECT DISTINCT league, teamID FROM baseballStats "
            "ORDER BY league, teamID LIMIT 60",
        )


class TestMisc:
    def test_explain(self, setup):
        engine, _ = setup
        resp = engine.execute(
            "EXPLAIN PLAN FOR SELECT SUM(runs) FROM baseballStats WHERE teamID = 'team_1'"
        )
        ops = [r[0] for r in resp["resultTable"]["rows"]]
        assert any("BROKER_REDUCE" in o for o in ops)
        # filter line names the chosen operator (sorted/inverted/full-scan)
        assert any(
            "FILTER_FULL_SCAN" in o or "FILTER_SORTED_INDEX" in o
            or "FILTER_INVERTED_INDEX" in o or "FILTER_PREDICATE" in o
            for o in ops
        )

    def test_stats_present(self, setup):
        engine, _ = setup
        resp = engine.execute("SELECT COUNT(*) FROM baseballStats WHERE league = 'AL'")
        assert resp["totalDocs"] == 6000
        assert resp["numSegmentsProcessed"] == 2
        assert 0 < resp["numDocsScanned"] < 6000

    def test_bloom_pruning(self, setup):
        engine, _ = setup
        resp = engine.execute(
            "SELECT COUNT(*) FROM baseballStats WHERE playerName = 'nonexistent_player'"
        )
        assert resp["resultTable"]["rows"][0][0] == 0
        assert resp["numSegmentsPrunedByServer"] == 2

    def test_unknown_table_error(self, setup):
        engine, _ = setup
        resp = engine.execute("SELECT COUNT(*) FROM nope")
        assert resp["exceptions"]


class TestQueryOptions:
    """Per-query SET options (QueryOptionsUtils analog)."""

    def test_num_groups_limit_option(self, setup):
        engine, _ = setup
        full = engine.execute(
            "SELECT playerName, COUNT(*) FROM baseballStats "
            "GROUP BY playerName LIMIT 1000")
        assert len(full["resultTable"]["rows"]) == 150
        capped = engine.execute(
            "SET numGroupsLimit = 10; "
            "SELECT playerName, COUNT(*) FROM baseballStats "
            "GROUP BY playerName LIMIT 1000")
        # per-segment cap of 10, merged across 2 segments: <= 20 groups
        assert 10 <= len(capped["resultTable"]["rows"]) <= 20


class TestVirtualColumns:
    """$docId / $segmentName / $hostName providers
    (segment/virtualcolumn/ analog)."""

    def test_doc_id_selection(self, setup):
        engine, _ = setup
        resp = engine.execute(
            "SELECT $docId, runs FROM baseballStats "
            "WHERE $docId < 3 AND $segmentName = 's0' ORDER BY $docId"
        )
        rows = resp["resultTable"]["rows"]
        assert [r[0] for r in rows] == [0, 1, 2]

    def test_segment_name_group_by(self, setup):
        engine, _ = setup
        resp = engine.execute(
            "SELECT $segmentName, COUNT(*) FROM baseballStats "
            "GROUP BY $segmentName ORDER BY $segmentName"
        )
        assert resp["resultTable"]["rows"] == [["s0", 3000], ["s1", 3000]]

    def test_host_name_defaults_to_hostname(self, setup):
        import socket

        engine, _ = setup
        resp = engine.execute(
            "SELECT DISTINCT $hostName FROM baseballStats")
        assert resp["resultTable"]["rows"] == [[socket.gethostname()]]

    def test_unknown_virtual_column_errors(self, setup):
        engine, _ = setup
        resp = engine.execute("SELECT $bogus FROM baseballStats")
        assert resp["exceptions"]


class TestHashing:
    def test_murmur3_32_known_vectors(self):
        """Deterministic murmur3_32 (ADVICE r1: builtin hash() is
        PYTHONHASHSEED-salted, breaking cross-process HLL merges)."""
        from pinot_tpu.ops.hll import murmur3_32

        assert murmur3_32(b"") == 0
        assert murmur3_32(b"hello") == 0x248BFA47
        assert murmur3_32(b"The quick brown fox jumps over the lazy dog") == 0x2E4FF723

    def test_string_hash_deterministic_across_calls(self):
        from pinot_tpu.ops.hll import hash32_np
        import numpy as np

        v = np.array(["alpha", "beta", "gamma", "alpha"])
        h1, h2 = hash32_np(v), hash32_np(v)
        assert np.array_equal(h1, h2)
        assert h1[0] == h1[3] and len({int(x) for x in h1[:3]}) == 3

    def test_star_tree_rejected_on_upsert_table(self):
        from pinot_tpu.common.table_config import (
            IndexingConfig,
            StarTreeIndexConfig,
            TableConfig,
            UpsertConfig,
        )
        import pytest

        with pytest.raises(ValueError, match="star_tree"):
            TableConfig(
                table_name="t",
                upsert=UpsertConfig(mode="FULL", comparison_column="ts"),
                indexing=IndexingConfig(
                    star_tree_configs=[
                        StarTreeIndexConfig(
                            dimensions_split_order=["a"],
                            function_column_pairs=["COUNT__*"],
                        )
                    ]
                ),
            )
