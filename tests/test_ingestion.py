"""Batch ingestion + quickstart + admin CLI.

Reference analogs: CSVRecordReaderTest / JSONRecordReaderTest,
IngestionJobLauncher standalone flow (SegmentGenerationJobRunner +
push), QuickStart smoke, PinotAdministrator command surface.
"""

import csv
import json
import os
import time

import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.ingestion.job import IngestionJobSpec, run_ingestion_job
from pinot_tpu.ingestion.readers import (
    CSVRecordReader,
    JSONRecordReader,
    create_record_reader,
    rows_to_columns,
)
from pinot_tpu.server.server import ServerInstance


def wait_until(cond, timeout=15.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


SCHEMA = Schema.build(
    name="t",
    dimensions=[("name", DataType.STRING)],
    multi_value_dimensions=[("tags", DataType.STRING)],
    metrics=[("score", DataType.DOUBLE)],
    datetimes=[("ts", DataType.LONG)],
)


class TestReaders:
    def test_csv_types_mv_and_nulls(self, tmp_path):
        p = tmp_path / "in.csv"
        p.write_text(
            "name,tags,score,ts\n"
            "alice,red;blue,1.5,100\n"
            "bob,,2.0,200\n"
            "carol,green,,300\n"
        )
        cols = CSVRecordReader().read_columns(str(p), SCHEMA)
        assert cols["name"] == ["alice", "bob", "carol"]
        assert cols["tags"] == [["red", "blue"], [], ["green"]]
        # empty cell stays None: the creator substitutes the default AND
        # records the null vector
        assert cols["score"] == [1.5, 2.0, None]
        assert cols["ts"] == [100, 200, 300]

    def test_json_lines_and_array(self, tmp_path):
        rows = [
            {"name": "a", "tags": ["x"], "score": 1, "ts": 10},
            {"name": "b", "tags": [], "score": 2.5, "ts": 20},
        ]
        pl = tmp_path / "in.jsonl"
        pl.write_text("\n".join(json.dumps(r) for r in rows))
        pa = tmp_path / "in.json"
        pa.write_text(json.dumps(rows))
        for path in (pl, pa):
            cols = JSONRecordReader().read_columns(str(path), SCHEMA)
            assert cols["name"] == ["a", "b"]
            assert cols["tags"] == [["x"], []]
            assert cols["score"] == [1.0, 2.5]

    def test_missing_column_stays_none_for_null_vector(self):
        cols = rows_to_columns([{"name": "a"}], SCHEMA)
        assert cols["score"] == [None]
        assert cols["ts"] == [None]
        assert cols["tags"] == [None]

    def test_unknown_format_raises(self):
        with pytest.raises(ValueError, match="unknown input format"):
            create_record_reader("xml")

    def test_parquet_roundtrip(self, tmp_path):
        pa = pytest.importorskip("pyarrow")
        pq = pytest.importorskip("pyarrow.parquet")
        table = pa.table({
            "name": ["a", "b"], "tags": [["x", "y"], []],
            "score": [1.0, 2.5], "ts": [10, 20],
        })
        p = tmp_path / "in.parquet"
        pq.write_table(table, str(p))
        cols = create_record_reader("parquet").read_columns(str(p), SCHEMA)
        assert cols["name"] == ["a", "b"]
        assert cols["tags"] == [["x", "y"], []]
        assert cols["score"] == [1.0, 2.5]
        assert cols["ts"] == [10, 20]


class TestIngestionJob:
    def test_job_builds_and_pushes_per_file(self, tmp_path):
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        servers = [ServerInstance("server_0", registry, str(tmp_path / "s0"),
                                  device_executor=None)]
        servers[0].start()
        broker = Broker(registry, timeout_s=10.0)
        try:
            schema = Schema.build(
                name="towns",
                dimensions=[("town", DataType.STRING)],
                metrics=[("pop", DataType.LONG)],
            )
            controller.add_table(TableConfig(table_name="towns"), schema)
            data = tmp_path / "files"
            data.mkdir()
            total = 0
            for i in range(3):
                with open(data / f"part_{i}.csv", "w", newline="") as f:
                    w = csv.writer(f)
                    w.writerow(["town", "pop"])
                    for j in range(10):
                        w.writerow([f"town{i}_{j}", 100 * i + j])
                        total += 100 * i + j
            spec = IngestionJobSpec(table_name="towns", input_dir=str(data),
                                    include_pattern="*.csv", format="csv")
            built = run_ingestion_job(spec, controller)
            assert len(built) == 3
            assert len(registry.segments("towns_OFFLINE")) == 3
            assert wait_until(
                lambda: len(registry.external_view("towns_OFFLINE")) == 3)
            r = broker.execute("SELECT COUNT(*), SUM(pop) FROM towns")
            assert not r.get("exceptions"), r
            assert r["resultTable"]["rows"] == [[30, total]]
        finally:
            broker.close()
            servers[0].stop()

    def test_job_spec_json_roundtrip(self, tmp_path):
        spec = IngestionJobSpec(table_name="t", input_dir="/x",
                                format="json", push=False)
        p = tmp_path / "spec.json"
        p.write_text(json.dumps(spec.to_json()))
        assert IngestionJobSpec.load(str(p)) == spec

    def test_no_matching_files_raises(self, tmp_path):
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        schema = Schema.build(name="e", dimensions=[("a", DataType.STRING)])
        controller.add_table(TableConfig(table_name="e"), schema)
        with pytest.raises(FileNotFoundError):
            run_ingestion_job(
                IngestionJobSpec(table_name="e", input_dir=str(tmp_path)),
                controller,
            )


class TestQuickstart:
    def test_quickstart_end_to_end(self, tmp_path):
        from pinot_tpu.tools.quickstart import run_quickstart

        lines = []
        handle = run_quickstart(work_dir=str(tmp_path / "qs"),
                                out=lines.append, device_executor=None)
        try:
            r = handle.execute("SELECT COUNT(*) FROM baseballStats")
            assert not r.get("exceptions"), r
            assert r["resultTable"]["rows"] == [[1000]]  # 2 files x 500 rows
            r = handle.execute(
                "SELECT teamID, SUM(runs) FROM baseballStats "
                "GROUP BY teamID ORDER BY SUM(runs) DESC LIMIT 3"
            )
            assert len(r["resultTable"]["rows"]) == 3
            # HTTP endpoint serves too
            import urllib.request

            req = urllib.request.Request(
                handle.http.url + "/query/sql",
                data=json.dumps(
                    {"sql": "SELECT COUNT(*) FROM baseballStats"}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert json.loads(resp.read())["resultTable"]["rows"] == [[1000]]
            assert any("example" in l or ">" in l for l in lines)
        finally:
            handle.stop()


class TestAdminCli:
    def test_multiprocess_style_flow_over_file_registry(self, tmp_path, capsys):
        """add-table + ingest + query against a FileRegistry shared with an
        in-process server (the CLI's multi-process contract, single-process
        here so the test stays hermetic)."""
        from pinot_tpu.cluster.registry import FileRegistry
        from pinot_tpu.tools.admin import main

        reg_path = str(tmp_path / "cluster.json")
        schema = Schema.build(
            name="towns",
            dimensions=[("town", DataType.STRING)],
            metrics=[("pop", DataType.LONG)],
        )
        schema_path = tmp_path / "schema.json"
        schema.save(str(schema_path))
        cfg_path = tmp_path / "table.json"
        cfg_path.write_text(json.dumps(TableConfig(table_name="towns").to_json()))

        assert main(["add-table", "--registry", reg_path,
                     "--schema", str(schema_path), "--config", str(cfg_path),
                     "--deep-store", str(tmp_path / "ds")]) == 0

        # a server joins the same registry file
        server = ServerInstance("server_0", FileRegistry(reg_path),
                                str(tmp_path / "s0"), device_executor=None)
        server.start()
        try:
            data = tmp_path / "files"
            data.mkdir()
            with open(data / "a.csv", "w", newline="") as f:
                w = csv.writer(f)
                w.writerow(["town", "pop"])
                w.writerow(["springfield", 30000])
                w.writerow(["shelbyville", 20000])
            spec_path = tmp_path / "job.json"
            spec_path.write_text(json.dumps(IngestionJobSpec(
                table_name="towns", input_dir=str(data)).to_json()))
            assert main(["ingest", "--registry", reg_path,
                         "--spec", str(spec_path),
                         "--deep-store", str(tmp_path / "ds")]) == 0
            reg = FileRegistry(reg_path)
            # FileRegistry polling + server sync can be slow under a loaded
            # full-suite run: give the view extra headroom
            assert wait_until(
                lambda: len(reg.external_view("towns_OFFLINE")) == 1,
                timeout=40)
            rc = main(["query", "--registry", reg_path,
                       "--sql", "SELECT SUM(pop) FROM towns"])
            out = capsys.readouterr().out
            assert rc == 0
            resp = json.loads(out[out.index("{"):])
            assert resp["resultTable"]["rows"] == [[50000]]
        finally:
            server.stop()


class TestParallelRunner:
    def test_parallel_builds_match_sequential(self, tmp_path):
        """parallelism > 1 fans per-file builds to spawned processes (the
        hadoop/spark runner role) and produces the same segments as the
        standalone runner."""
        import csv

        import numpy as np

        from pinot_tpu.broker.broker import Broker
        from pinot_tpu.cluster.registry import ClusterRegistry
        from pinot_tpu.common.datatypes import DataType
        from pinot_tpu.common.schema import Schema
        from pinot_tpu.common.table_config import TableConfig
        from pinot_tpu.controller.controller import Controller
        from pinot_tpu.ingestion.job import IngestionJobSpec, run_ingestion_job
        from pinot_tpu.server.server import ServerInstance
        from pinot_tpu.storage.segment import ImmutableSegment

        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        server = ServerInstance("s0", registry, str(tmp_path / "srv"),
                                device_executor=None)
        server.start()
        broker = Broker(registry)
        try:
            schema = Schema.build(
                name="pj", dimensions=[("k", DataType.STRING)],
                metrics=[("v", DataType.LONG)])
            controller.add_table(TableConfig(table_name="pj"), schema)
            data_dir = tmp_path / "in"
            data_dir.mkdir()
            total = 0
            for i in range(4):
                with open(data_dir / f"f{i}.csv", "w", newline="") as f:
                    w = csv.writer(f)
                    w.writerow(["k", "v"])
                    for j in range(200):
                        w.writerow([f"k{j % 5}", i * 1000 + j])
                        total += 1
            spec = IngestionJobSpec(
                table_name="pj", input_dir=str(data_dir), format="csv",
                output_dir=str(tmp_path / "segs"), parallelism=3)
            built = run_ingestion_job(spec, controller)
            assert len(built) == 4
            # order preserved: segment i carries file i's rows — file i's
            # values live in [i*1000, i*1000+200), so the VALUES pin it
            import numpy as _np

            for i in (0, 3):
                seg = ImmutableSegment(built[i])
                vals = _np.asarray(seg.values("v"))
                assert seg.n_docs == 200
                assert vals.min() == i * 1000 and vals.max() == i * 1000 + 199
            import time

            deadline = time.time() + 15
            while time.time() < deadline:
                r = broker.execute("SELECT COUNT(*), SUM(v) FROM pj")
                if not r.get("exceptions") \
                        and r["resultTable"]["rows"][0][0] == total:
                    break
                time.sleep(0.1)
            want_sum = sum(i * 1000 + j for i in range(4) for j in range(200))
            assert r["resultTable"]["rows"][0] == [total, want_sum]
        finally:
            broker.close()
            server.stop()
