"""Ingest-time record transforms/filtering + pyarrow input formats.

Reference analogs: recordtransformer/ExpressionTransformer +
FilterTransformer (TransformConfig/FilterConfig), pinot-parquet /
pinot-orc input-format plugins.
"""

import time

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import (
    IngestionConfig,
    StreamConfig,
    TableConfig,
    TableType,
    TransformConfig,
)
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.ingestion.transform import RecordTransformer


def _cfg(transforms=(), filter_fn=None, **kw):
    return TableConfig(
        table_name="t",
        ingestion=IngestionConfig(
            transform_configs=[TransformConfig(*t) for t in transforms],
            filter_function=filter_fn),
        **kw)


class TestRecordTransformer:
    def test_derived_from_source_only_field(self):
        # epochSeconds is NOT a schema column; the transform derives the
        # schema's millis column from it
        t = RecordTransformer(_cfg([("ts_ms", "epochSeconds * 1000")]))
        out = t.apply_row({"epochSeconds": 12, "x": "a"})
        assert out["ts_ms"] == 12000

    def test_chained_transforms_see_prior_outputs(self):
        t = RecordTransformer(_cfg([("a2", "a + 1"), ("a3", "a2 * 10")]))
        assert t.apply_row({"a": 4})["a3"] == 50

    def test_string_functions_and_case(self):
        t = RecordTransformer(_cfg([
            ("city_uc", "UPPER(city)"),
            ("tier", "CASE WHEN pop > 100 THEN 'big' ELSE 'small' END")]))
        out = t.apply_row({"city": "oslo", "pop": 500})
        assert out["city_uc"] == "OSLO" and out["tier"] == "big"

    def test_null_inputs_propagate(self):
        t = RecordTransformer(_cfg([("y", "x * 2")]))
        assert t.apply_row({})["y"] is None

    def test_filter_drops_rows(self):
        t = RecordTransformer(_cfg(filter_fn="pop < 10"))
        assert t.apply_row({"pop": 5}) is None
        assert t.apply_row({"pop": 50}) == {"pop": 50}
        rows = t.apply_rows([{"pop": 5}, {"pop": 50}, {"pop": 3}])
        assert rows == [{"pop": 50}]

    def test_inactive_passthrough(self):
        t = RecordTransformer(TableConfig(table_name="t"))
        assert not t.active
        row = {"a": 1}
        assert t.apply_row(row) is row

    def test_in_between_like_filters(self):
        # comparison forms outside the ops registry (r3 review)
        t = RecordTransformer(_cfg(filter_fn="country IN ('us', 'ca')"))
        assert t.apply_row({"country": "us"}) is None
        assert t.apply_row({"country": "de"}) == {"country": "de"}
        t = RecordTransformer(_cfg(filter_fn="v BETWEEN 10 AND 20"))
        assert t.apply_row({"v": 15}) is None
        assert t.apply_row({"v": 5}) == {"v": 5}
        t = RecordTransformer(_cfg(filter_fn="name LIKE 'tmp%'"))
        assert t.apply_row({"name": "tmp_x"}) is None
        assert t.apply_row({"name": "real"}) == {"name": "real"}
        t = RecordTransformer(_cfg(filter_fn="x IS NULL"))
        assert t.apply_row({}) is None
        assert t.apply_row({"x": 1}) == {"x": 1}

    def test_csv_strings_coerce_numeric(self):
        # CSV hands everything over as str: '1' + '2' must be 3, not '12'
        # (r3 review: numpy 2 silently concatenates unicode)
        t = RecordTransformer(_cfg([("s", "a + b")]))
        assert t.apply_row({"a": "1", "b": "2"})["s"] == 3
        t = RecordTransformer(_cfg(filter_fn="v > 5"))
        assert t.apply_row({"v": "3"}) == {"v": "3"}
        assert t.apply_row({"v": "9"}) is None

    def test_unknown_function_is_transform_error(self):
        from pinot_tpu.ingestion.transform import TransformError

        t = RecordTransformer(_cfg([("y", "NOSUCHFN(x)")]))
        with pytest.raises(TransformError, match="unknown function"):
            t.apply_row({"x": 1})

    def test_vectorized_batch_matches_row_path(self):
        rng = np.random.default_rng(2)
        rows = [{"a": int(a), "b": f"{b}", "city": c}
                for a, b, c in zip(rng.integers(0, 100, 500),
                                   rng.integers(0, 50, 500),
                                   np.array(["x", "y", "z"])[
                                       rng.integers(0, 3, 500)])]
        rows[7] = {"b": "1", "city": "x"}  # missing a: null propagates
        t = RecordTransformer(_cfg(
            [("s", "a + b"), ("cu", "UPPER(city)")],
            filter_fn="city = 'z'"))
        vec = t.apply_rows(rows)
        ref = [r for r in (t.apply_row(row) for row in rows) if r is not None]
        assert vec == ref


def wait_until(cond, timeout=10.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.05)
    return False


class TestRealtimeTransforms:
    def test_consume_with_transform_and_filter(self, tmp_path):
        from pinot_tpu.realtime.manager import RealtimeTableDataManager
        from pinot_tpu.stream.memory_stream import TopicRegistry

        TopicRegistry.delete("t_rt_transform")
        topic = TopicRegistry.create("t_rt_transform", 1)
        schema = Schema.build(name="t",
                              dimensions=[("kind", DataType.STRING)],
                              metrics=[("ms", DataType.LONG)])
        cfg = _cfg([("ms", "secs * 1000"),
                    ("kind", "LOWER(rawKind)")],
                   filter_fn="secs < 0",
                   table_type=TableType.REALTIME,
                   stream=StreamConfig(stream_type="memory",
                                       topic="t_rt_transform",
                                       decoder="json",
                                       segment_flush_threshold_rows=10_000))
        eng = QueryEngine(device_executor=None)
        mgr = RealtimeTableDataManager(schema, cfg, eng.table("t"),
                                       str(tmp_path / "rt"))
        mgr.start()
        try:
            topic.publish_json({"rawKind": "Click", "secs": 3})
            topic.publish_json({"rawKind": "VIEW", "secs": -1})  # filtered
            topic.publish_json({"rawKind": "View", "secs": 7})
            assert wait_until(lambda: not eng.execute(
                "SELECT COUNT(*) FROM t").get("exceptions") and eng.execute(
                "SELECT COUNT(*) FROM t")["resultTable"]["rows"] == [[2]])
            r = eng.execute("SELECT kind, ms FROM t ORDER BY ms")
            assert r["resultTable"]["rows"] == [["click", 3000],
                                                ["view", 7000]]
        finally:
            mgr.stop(commit_remaining=False)


class TestRealtimeTransformError:
    def test_config_bug_kills_partition_not_stream(self, tmp_path):
        """A broken transform must put the partition in ERROR, not silently
        drain the stream as poison messages (r3 review)."""
        from pinot_tpu.realtime.manager import RealtimeTableDataManager
        from pinot_tpu.stream.memory_stream import TopicRegistry

        TopicRegistry.delete("t_rt_broken")
        topic = TopicRegistry.create("t_rt_broken", 1)
        schema = Schema.build(name="t", dimensions=[("k", DataType.STRING)],
                              metrics=[("v", DataType.LONG)])
        cfg = _cfg([("v", "NOSUCHFN(x)")],
                   table_type=TableType.REALTIME,
                   stream=StreamConfig(stream_type="memory",
                                       topic="t_rt_broken", decoder="json"))
        eng = QueryEngine(device_executor=None)
        mgr = RealtimeTableDataManager(schema, cfg, eng.table("t"),
                                       str(tmp_path / "rt"))
        mgr.start()
        try:
            topic.publish_json({"k": "a", "x": 1})
            pm = mgr.partition_managers[0]
            assert wait_until(lambda: pm.state == pm.ERROR)
            assert pm.index_errors == 0  # not counted as poison
        finally:
            mgr.stop(commit_remaining=False)


class TestPyarrowFormats:
    def test_parquet_batch_ingestion(self, tmp_path):
        pa = pytest.importorskip("pyarrow")
        import pyarrow.parquet as pq

        from pinot_tpu.cluster.registry import ClusterRegistry
        from pinot_tpu.controller.controller import Controller
        from pinot_tpu.ingestion.job import IngestionJobSpec, run_ingestion_job
        from pinot_tpu.server.server import ServerInstance

        table = pa.table({
            "city": ["sf", "nyc", "sf"],
            "pop": [100, 200, 300],
            "secs": [1, 2, 3],
        })
        data = tmp_path / "files"
        data.mkdir()
        pq.write_table(table, str(data / "part0.parquet"))

        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        server = ServerInstance("s0", registry, str(tmp_path / "sd"),
                                device_executor=None)
        server.start()
        try:
            schema = Schema.build(name="t",
                                  dimensions=[("city", DataType.STRING)],
                                  metrics=[("pop", DataType.LONG),
                                           ("ms", DataType.LONG)])
            cfg = _cfg([("ms", "secs * 1000")])
            controller.add_table(cfg, schema)
            run_ingestion_job(IngestionJobSpec(
                table_name="t", input_dir=str(data),
                include_pattern="*.parquet", format="parquet"), controller)
            assert wait_until(
                lambda: len(registry.external_view("t_OFFLINE")) == 1)
            eng = server.engine
            r = eng.execute("SELECT city, SUM(pop), MAX(ms) FROM t_OFFLINE "
                            "GROUP BY city ORDER BY city")
            assert r["resultTable"]["rows"] == [["nyc", 200, 2000],
                                                ["sf", 400, 3000]]
        finally:
            server.stop()

    def test_orc_reader(self, tmp_path):
        pa = pytest.importorskip("pyarrow")
        orc = pytest.importorskip("pyarrow.orc")

        from pinot_tpu.ingestion.readers import create_record_reader

        table = pa.table({"k": ["a", "b"], "v": [1, 2]})
        path = str(tmp_path / "d.orc")
        orc.write_table(table, path)
        schema = Schema.build(name="t", dimensions=[("k", DataType.STRING)],
                              metrics=[("v", DataType.LONG)])
        cols = create_record_reader("orc").read_columns(path, schema)
        assert cols["k"] == ["a", "b"] and cols["v"] == [1, 2]
