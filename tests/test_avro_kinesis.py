"""Avro input format (pure-python codec) + Kinesis stream plugin (faked
boto3), in the style of test_kafka_stream.py / test_s3fs.py.

Reference analogs: pinot-plugins/pinot-input-format/pinot-avro/,
pinot-stream-ingestion/pinot-kinesis/, SimpleAvroMessageDecoder.
"""

import sys
import types

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import StreamConfig, TableConfig
from pinot_tpu.ingestion import avro_io


AVRO_SCHEMA = {
    "type": "record",
    "name": "Event",
    "fields": [
        {"name": "user", "type": "string"},
        {"name": "clicks", "type": "long"},
        {"name": "score", "type": "double"},
        {"name": "ok", "type": "boolean"},
        {"name": "tags", "type": {"type": "array", "items": "string"}},
        {"name": "attrs", "type": {"type": "map", "values": "int"}},
        {"name": "maybe", "type": ["null", "long"]},
        {"name": "blob", "type": "bytes"},
    ],
}

ROWS = [
    {"user": "ué", "clicks": 2**40, "score": 1.5, "ok": True,
     "tags": ["a", "b"], "attrs": {"k": 1}, "maybe": None, "blob": b"\x00\x01"},
    {"user": "v", "clicks": -7, "score": -0.25, "ok": False,
     "tags": [], "attrs": {}, "maybe": 42, "blob": b""},
    {"user": "w", "clicks": 0, "score": 0.0, "ok": True,
     "tags": ["x"], "attrs": {"a": -1, "b": 2}, "maybe": -(2**50),
     "blob": b"zz"},
]


class TestAvroCodec:
    @pytest.mark.parametrize("codec", ["null", "deflate"])
    def test_container_roundtrip(self, tmp_path, codec):
        p = str(tmp_path / f"f_{codec}.avro")
        avro_io.write_container(p, AVRO_SCHEMA, ROWS, codec=codec)
        assert avro_io.read_container(p) == ROWS

    def test_binary_record_roundtrip(self):
        import json

        dec = avro_io.binary_decoder_for(json.dumps(AVRO_SCHEMA))
        for r in ROWS:
            assert dec(avro_io.encode_record(AVRO_SCHEMA, r)) == r

    def test_record_reader_registered(self, tmp_path):
        from pinot_tpu.ingestion.readers import create_record_reader

        p = str(tmp_path / "f.avro")
        avro_io.write_container(p, AVRO_SCHEMA, ROWS)
        rows = create_record_reader("avro").read_rows(p)
        assert [r["user"] for r in rows] == ["ué", "v", "w"]

    def test_batch_ingestion_end_to_end(self, tmp_path):
        """Avro files → segment → query (the pinot-avro batch path)."""
        from pinot_tpu.engine.engine import QueryEngine
        from pinot_tpu.ingestion.readers import create_record_reader, rows_to_columns
        from pinot_tpu.storage.creator import build_segment

        schema = Schema.build(
            name="ev",
            dimensions=[("user", DataType.STRING)],
            metrics=[("clicks", DataType.LONG)],
        )
        avro_schema = avro_io.schema_for_pinot(schema)
        rows = [{"user": f"u{i % 5}", "clicks": i} for i in range(1000)]
        p = str(tmp_path / "data.avro")
        avro_io.write_container(p, avro_schema, rows, codec="deflate")

        read = create_record_reader("avro").read_rows(p)
        cols = rows_to_columns(read, schema)
        seg = build_segment(schema, cols, str(tmp_path / "seg"),
                            TableConfig(table_name="ev"), "s0")
        eng = QueryEngine(device_executor=None)
        eng.add_segment("ev", seg)
        r = eng.execute("SELECT user, SUM(clicks) FROM ev GROUP BY user "
                        "ORDER BY user")
        assert not r.get("exceptions"), r
        want = {f"u{j}": sum(i for i in range(1000) if i % 5 == j)
                for j in range(5)}
        assert [(row[0], row[1]) for row in r["resultTable"]["rows"]] == \
            sorted((k, float(v)) for k, v in want.items())

    def test_avro_stream_decoder(self):
        import json

        cfg = StreamConfig(
            stream_type="memory", topic="t", decoder="avro",
            properties={"avro.schema": json.dumps(AVRO_SCHEMA)})
        from pinot_tpu.stream.spi import get_decoder

        dec = get_decoder("avro", cfg)
        out = dec(avro_io.encode_record(AVRO_SCHEMA, ROWS[0]))
        assert out["user"] == "ué" and out["clicks"] == 2**40

    def test_missing_stream_schema_raises(self):
        from pinot_tpu.stream.spi import get_decoder

        cfg = StreamConfig(stream_type="memory", topic="t", decoder="avro")
        with pytest.raises(KeyError):
            get_decoder("avro", cfg)


# ---------------------------------------------------------------------------
# faked boto3 kinesis
# ---------------------------------------------------------------------------


class _FakeKinesisClient:
    def __init__(self, streams):
        # streams: {name: {shard_id: [ (seq:int, data:bytes, pkey) ]}}
        self._streams = streams
        self._iters = {}
        self._n = 0
        self.closed = False

    def list_shards(self, StreamName=None, NextToken=None):
        return {"Shards": [{"ShardId": sid}
                           for sid in sorted(self._streams[StreamName])]}

    def get_shard_iterator(self, StreamName, ShardId, ShardIteratorType,
                           StartingSequenceNumber=None):
        self._n += 1
        token = f"it{self._n}"
        if ShardIteratorType == "TRIM_HORIZON":
            pos = 0
        elif ShardIteratorType == "AFTER_SEQUENCE_NUMBER":
            pos = int(StartingSequenceNumber) + 1
        else:
            raise AssertionError(ShardIteratorType)
        self._iters[token] = (StreamName, ShardId, pos)
        return {"ShardIterator": token}

    def get_records(self, ShardIterator, Limit=None):
        stream, shard, pos = self._iters.pop(ShardIterator)
        log = self._streams[stream][shard]
        batch = [r for r in log if r[0] >= pos][:100]
        next_pos = (batch[-1][0] + 1) if batch else pos
        self._n += 1
        token = f"it{self._n}"
        self._iters[token] = (stream, shard, next_pos)
        return {
            "Records": [
                {"SequenceNumber": str(seq), "Data": data,
                 "PartitionKey": pk, "ApproximateArrivalTimestamp": None}
                for seq, data, pk in batch
            ],
            "NextShardIterator": token,
        }

    def close(self):
        self.closed = True


@pytest.fixture()
def fake_boto3(monkeypatch):
    streams = {
        "events": {
            "shardId-000": [(100, b'{"v": 1}', "a"), (101, b'{"v": 2}', "b"),
                            (105, b'{"v": 3}', "c")],
            "shardId-001": [(500, b'{"v": 10}', "d")],
        }
    }
    mod = types.ModuleType("boto3")
    mod.client = lambda service, **kw: _FakeKinesisClient(streams)
    monkeypatch.setitem(sys.modules, "boto3", mod)
    # the plugin may already be registered from a previous test run in this
    # process; re-import is harmless (idempotent register)
    return streams


class TestKinesisPlugin:
    def _cfg(self):
        return StreamConfig(stream_type="kinesis", topic="events",
                            decoder="json",
                            properties={"aws.region": "us-test-1"})

    def test_partition_count_and_earliest(self, fake_boto3):
        from pinot_tpu.stream.spi import create_consumer_factory

        f = create_consumer_factory(self._cfg())
        assert f.partition_count() == 2
        assert f.earliest_offset(0).value == 0

    def test_fetch_resume_and_next_offset(self, fake_boto3):
        from pinot_tpu.stream.spi import create_consumer_factory
        from pinot_tpu.stream.spi import StreamPartitionMsgOffset

        f = create_consumer_factory(self._cfg())
        c = f.create_partition_consumer(0)
        batch = c.fetch_messages(StreamPartitionMsgOffset(0), 100)
        assert [m.payload for m in batch.messages] == \
            [b'{"v": 1}', b'{"v": 2}', b'{"v": 3}']
        # sequence-number offsets: next = last seq + 1
        assert batch.next_offset.value == 106
        # resume from a checkpoint mid-stream replays only the tail
        batch2 = c.fetch_messages(StreamPartitionMsgOffset(102), 100)
        assert [m.payload for m in batch2.messages] == [b'{"v": 3}']
        c.close()

    def test_second_shard_is_partition_1(self, fake_boto3):
        from pinot_tpu.stream.spi import create_consumer_factory
        from pinot_tpu.stream.spi import StreamPartitionMsgOffset

        f = create_consumer_factory(self._cfg())
        c = f.create_partition_consumer(1)
        batch = c.fetch_messages(StreamPartitionMsgOffset(0), 100)
        assert [m.payload for m in batch.messages] == [b'{"v": 10}']
        assert batch.next_offset.value == 501

    def test_gating_error_without_boto3(self, monkeypatch):
        # sys.modules[name] = None makes `import boto3` raise ImportError,
        # driving the REAL gating path (no mocking of _boto3 itself)
        monkeypatch.setitem(sys.modules, "boto3", None)
        from pinot_tpu.stream import kinesis_stream

        with pytest.raises(RuntimeError, match="boto3"):
            kinesis_stream.KinesisConsumerFactory(self._cfg())

    def test_realtime_consume_via_kinesis(self, fake_boto3, tmp_path):
        """Full realtime manager loop over the faked kinesis stream."""
        import time

        from pinot_tpu.engine.engine import QueryEngine
        from pinot_tpu.realtime.manager import RealtimeTableDataManager

        schema = Schema.build(name="ev", dimensions=[],
                              metrics=[("v", DataType.INT)])
        cfg = TableConfig(
            table_name="ev", table_type=None,
            stream=StreamConfig(
                stream_type="kinesis", topic="events", decoder="json",
                segment_flush_threshold_rows=100_000,
                segment_flush_threshold_seconds=3600,
                properties={"aws.region": "us-test-1"}),
        )
        eng = QueryEngine(device_executor=None)
        mgr = RealtimeTableDataManager(schema, cfg, eng.table("ev"),
                                       str(tmp_path / "rt"))
        mgr.start(partitions=[0, 1])
        try:
            deadline = time.time() + 10
            while time.time() < deadline:
                r = eng.execute("SELECT COUNT(*), SUM(v) FROM ev")
                if not r.get("exceptions") and \
                        r["resultTable"]["rows"][0][0] == 4:
                    break
                time.sleep(0.1)
            assert r["resultTable"]["rows"][0] == [4, 16.0], r
        finally:
            mgr.stop(commit_remaining=False)
