"""The 14 transform-enum tail functions (VERDICT r4 missing #6):
QUARTER / WEEK_OF_YEAR / DAY_OF_YEAR / YEAR_OF_WEEK / MILLISECOND,
ATAN2 / COT / ROUND_DECIMAL / TRUNCATE, JSONEXTRACTKEY, INIDSET,
GEOTOH3(grid role), ST_EQUALS, ST_GEOMETRY_TYPE — oracle-checked against
python datetime.isocalendar / math / json.
"""

import datetime as dt
import json
import math

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment

N = 2_000


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    # span several year boundaries so ISO week/year-of-week edges appear
    base = int(dt.datetime(2019, 12, 20).timestamp() * 1000)
    span = 5 * 366 * 86_400_000
    ts = (base + rng.integers(0, span, N)).astype(np.int64)
    return {
        "ts": ts,
        "x": rng.normal(0, 10, N).astype(np.float64),
        "y": (rng.normal(0, 10, N) + 0.001).astype(np.float64),
        "doc": np.array([json.dumps(
            {"store": {f"k{j}": j for j in range(i % 4 + 1)},
             "arr": list(range(i % 3))}) for i in range(N)]),
        "lon": rng.uniform(-179, 179, N).astype(np.float64),
        "lat": rng.uniform(-89, 89, N).astype(np.float64),
        "uid": rng.integers(0, 50, N).astype(np.int64),
    }


@pytest.fixture(scope="module")
def eng(tmp_path_factory, data):
    schema = Schema.build(
        name="tt", dimensions=[("doc", DataType.STRING)],
        metrics=[("x", DataType.DOUBLE), ("y", DataType.DOUBLE),
                 ("lon", DataType.DOUBLE), ("lat", DataType.DOUBLE),
                 ("uid", DataType.LONG)],
        datetimes=[("ts", DataType.LONG)])
    d = str(tmp_path_factory.mktemp("tt") / "s0")
    seg = build_segment(schema, data, d)
    e = QueryEngine(device_executor=None)
    e.add_segment("tt", seg)
    return e


def col(e, expr, extra=""):
    r = e.execute(f"SELECT {expr} FROM tt {extra} LIMIT {N}")
    assert not r.get("exceptions"), r
    return [row[0] for row in r["resultTable"]["rows"]]


def test_datetime_parts(eng, data):
    got_q = col(eng, "QUARTER(ts)")
    got_w = col(eng, "WEEKOFYEAR(ts)")
    got_doy = col(eng, "DAYOFYEAR(ts)")
    got_yow = col(eng, "YEAROFWEEK(ts)")
    got_ms = col(eng, "MILLISECOND(ts)")
    for i, t in enumerate(data["ts"].tolist()):
        d = dt.datetime.fromtimestamp(t / 1000.0, dt.timezone.utc)
        iso = dt.date(d.year, d.month, d.day).isocalendar()
        assert got_q[i] == (d.month - 1) // 3 + 1
        assert got_w[i] == iso[1], (d, got_w[i], iso)
        assert got_yow[i] == iso[0], (d, got_yow[i], iso)
        assert got_doy[i] == d.timetuple().tm_yday
        assert got_ms[i] == t % 1000


def test_datetime_aliases(eng):
    assert col(eng, "WEEK(ts)") == col(eng, "WEEKOFYEAR(ts)")
    assert col(eng, "DOY(ts)") == col(eng, "DAYOFYEAR(ts)")
    assert col(eng, "YOW(ts)") == col(eng, "YEAROFWEEK(ts)")


def test_atan2_cot(eng, data):
    got = col(eng, "ATAN2(x, y)")
    want = np.arctan2(data["x"], data["y"])
    np.testing.assert_allclose(got, want, rtol=1e-12)
    got = col(eng, "COT(y)")
    np.testing.assert_allclose(got, 1.0 / np.tan(data["y"]), rtol=1e-9)


def test_round_decimal_truncate(eng, data):
    got = col(eng, "ROUNDDECIMAL(x, 2)")
    for g, v in zip(got, data["x"].tolist()):
        want = math.copysign(math.floor(abs(v) * 100 + 0.5) / 100, v)
        assert g == pytest.approx(want, abs=1e-12), (v, g, want)
    got = col(eng, "TRUNCATE(x, 1)")
    for g, v in zip(got, data["x"].tolist()):
        want = math.copysign(math.floor(abs(v) * 10) / 10, v)
        assert g == pytest.approx(want, abs=1e-12)
    # 1-arg forms: Math.round / truncate-to-integer
    assert col(eng, "ROUNDDECIMAL(x)") == [
        float(math.floor(v + 0.5)) for v in data["x"].tolist()]
    assert col(eng, "TRUNCATE(x)") == [
        float(math.copysign(math.floor(abs(v)), v)) for v in data["x"].tolist()]


def test_half_up_vs_half_even():
    """The reference rounds HALF_UP (2.5 -> 3), numpy rounds half-even
    (2.5 -> 2): the spec must match the reference."""
    from pinot_tpu.ops.transform import get_function

    f = get_function("rounddecimal")
    np.testing.assert_array_equal(
        f.np_fn(np.array([2.5, 3.5, -2.5, 0.125]), 0),
        [3.0, 4.0, -3.0, 0.0])
    np.testing.assert_array_equal(
        f.np_fn(np.array([0.125, 0.135]), 2), [0.13, 0.14])


def test_jsonextractkey(eng, data):
    got = col(eng, "JSONEXTRACTKEY(doc, '$.store.*')")
    for g, s in zip(got, data["doc"].tolist()):
        keys = list(json.loads(s)["store"].keys())
        assert g == [f"$['store']['{k}']" for k in keys], (s, g)
    got = col(eng, "JSONEXTRACTKEY(doc, '$.arr[*]')")
    for g, s in zip(got, data["doc"].tolist()):
        n = len(json.loads(s)["arr"])
        assert g == [f"$['arr'][{j}]" for j in range(n)]


def test_inidset_roundtrip(eng, data):
    """IDSET aggregation output feeds INIDSET filtering (the reference's
    IdSet produce/consume pair)."""
    r = eng.execute("SELECT IDSET(uid) FROM tt WHERE uid < 10")
    blob = r["resultTable"]["rows"][0][0]
    got = col(eng, "uid", f"WHERE INIDSET(uid, '{blob}') = true")
    assert got and all(u < 10 for u in got)
    assert len(got) == int((data["uid"] < 10).sum())


def test_geotoh3_grid_cells(eng, data):
    got5 = col(eng, "GEOTOH3(lon, lat, 5)")
    got9 = col(eng, "GEOTOH3(lon, lat, 9)")
    assert len(set(got5)) < len(set(got9))  # coarser at lower resolution
    # same cell iff same floor at that resolution
    res_deg = 360.0 / 32
    want = {}
    for i in range(N):
        key = (math.floor(data["lat"][i] / res_deg),
               math.floor(data["lon"][i] / res_deg))
        want.setdefault(key, set()).add(got5[i])
    assert all(len(cells) == 1 for cells in want.values())
    # 2-arg form over a POINT expression
    got_pt = col(eng, "GEOTOH3(ST_POINT(lon, lat), 5)")
    assert got_pt == got5


def test_st_equals_and_geometry_type(eng):
    got = col(eng, "ST_EQUALS(ST_POINT(lon, lat), ST_POINT(lon, lat))")
    assert all(bool(g) for g in got)
    # swapped coordinates never match (continuous uniforms: lon != lat)
    got = col(eng, "ST_EQUALS(ST_POINT(lon, lat), ST_POINT(lat, lon))")
    assert not any(bool(g) for g in got)
    assert set(col(eng, "ST_GEOMETRYTYPE(ST_POINT(lon, lat))")) == {"Point"}
    from pinot_tpu.ops.geo import st_geometry_type

    assert list(st_geometry_type(
        ["POLYGON ((0 0, 1 0, 1 1, 0 0))", "MULTIPOINT (1 2)"])) \
        == ["Polygon", "MultiPoint"]
