"""Token-bucket priority scheduler: fairness, accounting, selection.

Reference analogs: tokenbucket/TokenPriorityScheduler.java:1,
MultiLevelPriorityQueue, resources/BoundedAccountingExecutor — a heavy
tenant drains its token bucket and yields slots to light tenants instead
of starving them.
"""

import threading
import time

import numpy as np
import pytest

from pinot_tpu.engine.scheduler import (
    QueryScheduler,
    SchedulerSaturated,
    TokenBucketScheduler,
    make_scheduler,
)


def _percentile(xs, p):
    return float(np.percentile(np.asarray(xs), p))


def _light_latencies(sched, n=30, work_s=0.004):
    """Submit light-tenant queries at a steady trickle, one at a time
    (closed loop), returning end-to-end latencies."""
    lats = []
    for _ in range(n):
        t0 = time.perf_counter()
        sched.run(lambda: time.sleep(work_s), group="light")
        lats.append(time.perf_counter() - t0)
        time.sleep(0.002)
    return lats


class TestTokenBucketFairness:
    def test_heavy_tenant_cannot_starve_light(self):
        """VERDICT round-3 acceptance: heavy tenant at saturation QPS must
        not push the light tenant's p99 past 2x its solo p99 (+ a fixed
        5ms scheduling epsilon for CI jitter)."""
        def solo_sched():
            return TokenBucketScheduler(
                max_concurrent=2, max_queued=64,
                rate_ms_per_s=50.0, burst_ms=100.0)

        solo = _light_latencies(solo_sched())
        solo_p99 = _percentile(solo, 99)

        sched = solo_sched()
        stop = threading.Event()

        def heavy_loop():
            while not stop.is_set():
                try:
                    sched.run(lambda: time.sleep(0.05), group="heavy",
                              queue_timeout_s=0.5)
                except SchedulerSaturated:
                    pass

        threads = [threading.Thread(target=heavy_loop, daemon=True)
                   for _ in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.15)  # let the heavy tenant overdraw its bucket
        try:
            contended = _light_latencies(sched)
        finally:
            stop.set()
            for t in threads:
                t.join(2)
        contended_p99 = _percentile(contended, 99)
        assert contended_p99 <= 2 * solo_p99 + 0.005, (
            f"light p99 {contended_p99 * 1e3:.1f}ms vs solo "
            f"{solo_p99 * 1e3:.1f}ms — heavy tenant starved the light one")
        # and the heavy tenant is overdrawn while light stays solvent
        gs = sched.group_stats()
        assert gs["heavy"]["executed"] > 0
        assert gs["heavy"]["tokens_ms"] < gs["light"]["tokens_ms"]

    def test_fifo_within_group(self):
        sched = TokenBucketScheduler(max_concurrent=1, max_queued=16)
        order = []
        hold = threading.Event()
        t0 = threading.Thread(
            target=lambda: sched.run(lambda: hold.wait(2), group="g"))
        t0.start()
        time.sleep(0.05)
        threads = []
        for i in range(4):
            th = threading.Thread(
                target=lambda i=i: sched.run(
                    lambda: order.append(i), group="g"))
            th.start()
            time.sleep(0.02)  # deterministic arrival order
            threads.append(th)
        hold.set()
        for th in threads:
            th.join(3)
        assert order == [0, 1, 2, 3]


class TestAccountingAndSelection:
    def test_stats_out_accounting(self):
        def busy():
            t = time.thread_time()
            while time.thread_time() - t < 0.01:
                pass
            return 42

        # both schedulers publish the wait BEFORE fn runs (so fn can fold
        # it into the response it serializes)
        for sched in (QueryScheduler(), TokenBucketScheduler()):
            acct = {}
            assert sched.run(busy, stats_out=acct, group="t1") == 42
            assert acct["scheduler_wait_ms"] >= 0
        # the token bucket additionally reports CPU post-fn (it needs the
        # measurement for group accounting anyway)
        assert acct["thread_cpu_time_ns"] >= 5_000_000

    def test_group_stats_snapshot(self):
        sched = TokenBucketScheduler(rate_ms_per_s=100, burst_ms=200)
        sched.run(lambda: time.sleep(0.01), group="tableA")
        sched.run(lambda: None, group="tableB")
        gs = sched.group_stats()
        assert gs["tableA"]["executed"] == 1
        assert gs["tableB"]["executed"] == 1
        assert gs["tableA"]["wall_ms_total"] >= 10
        assert gs["tableA"]["tokens_ms"] < gs["tableB"]["tokens_ms"]

    def test_queue_cap_rejects(self):
        sched = TokenBucketScheduler(max_concurrent=1, max_queued=1,
                                     queue_timeout_s=0.05)
        hold = threading.Event()
        t = threading.Thread(
            target=lambda: sched.run(lambda: hold.wait(2), group="g"))
        t.start()
        time.sleep(0.05)
        waiter = threading.Thread(target=lambda: _swallow(
            lambda: sched.run(lambda: None, group="g", queue_timeout_s=2)))
        waiter.start()
        time.sleep(0.05)
        with pytest.raises(SchedulerSaturated):
            sched.run(lambda: None, group="g")  # queue already full
        hold.set()
        t.join(2)
        waiter.join(3)
        assert sched.num_rejected >= 1

    def test_make_scheduler_selection(self):
        assert isinstance(make_scheduler("fcfs", 4, 8), QueryScheduler)
        assert isinstance(make_scheduler("tokenbucket", 4, 8),
                          TokenBucketScheduler)
        with pytest.raises(ValueError):
            make_scheduler("nope", 4, 8)


def _swallow(fn):
    try:
        fn()
    except SchedulerSaturated:
        pass


class TestServerIntegration:
    def test_server_ships_cpu_accounting(self, tmp_path):
        """threadCpuTimeNs + schedulerWaitMs flow server -> wire -> broker
        response (reference DataTable V3 metadata)."""
        from pinot_tpu.broker.broker import Broker
        from pinot_tpu.cluster.registry import ClusterRegistry
        from pinot_tpu.common.datatypes import DataType
        from pinot_tpu.common.schema import Schema
        from pinot_tpu.common.table_config import TableConfig
        from pinot_tpu.controller.controller import Controller
        from pinot_tpu.server.server import ServerInstance
        from pinot_tpu.storage.creator import build_segment

        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        server = ServerInstance("s0", registry, str(tmp_path / "srv"),
                                device_executor=None,
                                scheduler_name="tokenbucket")
        server.start()
        broker = Broker(registry)
        try:
            schema = Schema.build(
                name="t", dimensions=[("k", DataType.STRING)],
                metrics=[("v", DataType.INT)])
            cfg = TableConfig(table_name="t")
            controller.add_table(cfg, schema)
            d = str(tmp_path / "seg")
            # enough rows that the query's CPU burst reliably crosses the
            # container clock's thread_time granularity (a 1000-row query
            # can finish inside one tick and report a flaky 0)
            n = 200_000
            build_segment(schema, {
                "k": np.array(["a", "b"] * (n // 2)),
                "v": np.arange(n, dtype=np.int32)}, d, cfg, "t_0")
            controller.upload_segment("t", d)
            deadline = time.time() + 10
            r = None
            while time.time() < deadline:
                r = broker.execute("SELECT k, SUM(v) FROM t GROUP BY k")
                if not r.get("exceptions") and r["threadCpuTimeNs"] > 0:
                    break
                time.sleep(0.1)
            assert not r.get("exceptions"), r
            assert r["threadCpuTimeNs"] > 0
            assert r["schedulerWaitMs"] >= 0
            from pinot_tpu.engine.scheduler import TokenBucketScheduler

            assert isinstance(server.scheduler, TokenBucketScheduler)
            # group = table as written in the SQL (TableBasedGroupMapper)
            assert "t" in server.scheduler.group_stats()
        finally:
            broker.close()
            server.stop()
