"""Distributed stage-2 exchange (ISSUE 16) — differential + chaos suite.

The mailbox exchange (query2/exchange.py + server ExecuteStage /
ExchangeTransfer RPCs) must be invisible to results: a fact-fact join
under ``SET joinStrategy = 'distributed'`` answers bit-identically to the
broker-local SHUFFLE mirror and a sqlite3 oracle — sealed + consuming
segments, host-only + mesh-device servers, with and without warm-tier
spills (simulated via a tiny mailbox buffer). Also pins:

- the wire codec + stable partition hash (value-identical keys hash
  equal across dtypes; empty partitions still ship dtyped),
- the planner demotion past BROADCAST_MAX_BUILD_ROWS (effective strategy
  + joinStrategyDemoted reported),
- EXPLAIN / EXPLAIN ANALYZE rendering of the DISTRIBUTED boundary with
  partition/shipped/spill actuals,
- chaos at the ``exchange.transfer`` seam: error → replica retry with
  PEER attribution; blackhole → deadline-bounded; unrecoverable → typed
  partialResult, never a hang.
"""

import math
import sqlite3
import time

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common import faults
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import StreamConfig, TableConfig, TableType
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment

N_FACT = 3000
N_SHIP = 900
N_KEYS = 50


def _wait_until(cond, timeout=15.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


def _norm(v):
    if v is None:
        return None
    if isinstance(v, bool):
        return float(v)
    if isinstance(v, (int, float)):
        f = float(v)
        return None if math.isnan(f) else round(f, 6)
    return v


def _rows(resp):
    assert not resp.get("exceptions"), resp.get("exceptions")
    return [[_norm(v) for v in r] for r in resp["resultTable"]["rows"]]


def _data():
    rng = np.random.default_rng(23)
    # integer measures only: float SUM partials merge in partition order,
    # which is not bit-stable across fan-outs (documented in PARITY.md)
    fact = {
        "k": rng.integers(0, N_KEYS + 6, N_FACT).astype(np.int32),
        "status": np.array(["open", "paid", "void"])[
            rng.integers(0, 3, N_FACT)],
        "v": rng.integers(1, 40, N_FACT).astype(np.int32),
    }
    ship = {
        "k2": rng.integers(0, N_KEYS, N_SHIP).astype(np.int32),
        "mode": np.array(["air", "sea", "rail"])[
            rng.integers(0, 3, N_SHIP)],
        "w": rng.integers(1, 9, N_SHIP).astype(np.int32),
    }
    return fact, ship


def _schemas():
    fact = Schema.build(
        name="fa",
        dimensions=[("k", DataType.INT), ("status", DataType.STRING)],
        metrics=[("v", DataType.INT)],
    )
    ship = Schema.build(
        name="fb",
        dimensions=[("k2", DataType.INT), ("mode", DataType.STRING)],
        metrics=[("w", DataType.INT)],
    )
    return fact, ship


def _make_cluster(tmp, device_executors=None):
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp / "ds"))
    devs = device_executors or [None, None]
    servers = [
        ServerInstance(f"server_{i}", registry, str(tmp / f"s{i}"),
                       device_executor=devs[i])
        for i in range(2)
    ]
    for s in servers:
        s.start()
    broker = Broker(registry, timeout_s=15.0)
    fact, ship = _data()
    fact_schema, ship_schema = _schemas()
    for name, schema, data, keycol in (("fa", fact_schema, fact, "k"),
                                       ("fb", ship_schema, ship, "k2")):
        cfg = TableConfig(table_name=name, replication=2)
        controller.add_table(cfg, schema)
        n = len(data[keycol])
        for i, sl in enumerate([slice(0, n // 2), slice(n // 2, n)]):
            build_segment(schema, {k: v[sl] for k, v in data.items()},
                          str(tmp / f"{name}up{i}"), cfg, f"{name}{i}")
            controller.upload_segment(name, str(tmp / f"{name}up{i}"))
    assert _wait_until(
        lambda: len(registry.external_view("fa_OFFLINE")) == 2
        and len(registry.external_view("fb_OFFLINE")) == 2)
    con = sqlite3.connect(":memory:")
    con.execute("CREATE TABLE fa (k INT, status TEXT, v INT)")
    con.executemany("INSERT INTO fa VALUES (?,?,?)",
                    list(zip(*(fact[c].tolist()
                               for c in ("k", "status", "v")))))
    con.execute("CREATE TABLE fb (k2 INT, mode TEXT, w INT)")
    con.executemany("INSERT INTO fb VALUES (?,?,?)",
                    list(zip(*(ship[c].tolist()
                               for c in ("k2", "mode", "w")))))
    return registry, controller, servers, broker, con


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("exchange")
    registry, controller, servers, broker, con = _make_cluster(tmp)
    yield registry, controller, servers, broker, con
    broker.close()
    for s in servers:
        s.stop()


def _reset_failures(broker):
    for inst in ("server_0", "server_1"):
        for _ in range(4):
            broker.failures.mark_success(inst)


GROUP_SQL = ("SELECT b.mode, COUNT(*), SUM(a.v), SUM(b.w) FROM fa a "
             "JOIN fb b ON a.k = b.k2 WHERE a.status = 'paid' "
             "GROUP BY b.mode ORDER BY b.mode")
SELECT_SQL = ("SELECT a.k, b.mode, a.v FROM fa a "
              "JOIN fb b ON a.k = b.k2 WHERE a.status = 'void' "
              "ORDER BY a.k, b.mode, a.v LIMIT 40")
LEFT_SQL = ("SELECT a.k, COUNT(*), SUM(b.w) FROM fa a "
            "LEFT JOIN fb b ON a.k = b.k2 WHERE a.status = 'open' "
            "GROUP BY a.k ORDER BY a.k LIMIT 30")


class TestDistributedParity:
    @pytest.mark.parametrize("sql", [GROUP_SQL, SELECT_SQL, LEFT_SQL],
                             ids=["group_by", "selection", "left_join"])
    def test_parity_vs_local_and_oracle(self, cluster, sql):
        _, _, _, broker, con = cluster
        oracle = [[_norm(v) for v in r] for r in con.execute(sql)]
        local = broker.execute(f"SET joinStrategy = 'shuffle'; {sql}")
        dist = broker.execute(f"SET joinStrategy = 'distributed'; {sql}")
        assert _rows(local) == oracle
        assert _rows(dist) == oracle
        assert dist["joinStrategy"] == "DISTRIBUTED"
        assert local["joinStrategy"] == "SHUFFLE"

    def test_exchange_counters(self, cluster):
        _, _, _, broker, _ = cluster
        local = broker.execute(f"SET joinStrategy = 'shuffle'; {GROUP_SQL}")
        dist = broker.execute(
            f"SET joinStrategy = 'distributed'; {GROUP_SQL}")
        assert dist["numServersQueried"] == 2
        assert dist["numServersResponded"] == 2
        assert dist["numStages"] == 2
        assert dist["numPartitionsShipped"] > 0
        assert dist["exchangeBytes"] > 0
        assert dist["exchangeSpillCount"] == 0
        assert dist["numJoinedRows"] == local["numJoinedRows"]
        ex = dist["exchange"]
        assert ex["numWorkers"] == 2
        assert ex["partitions"] == 4  # 2x workers
        assert dist["joinFanout"] == 4
        per = ex["servers"]
        assert set(per) == {"server_0", "server_1"}
        assert sum(v["stage2Rows"] for v in per.values()) \
            == dist["numJoinedRows"]
        # every worker scanned its share of both leaves
        total_leaf = {}
        for v in per.values():
            for alias, n in v["leafRows"].items():
                total_leaf[alias] = total_leaf.get(alias, 0) + n
        assert total_leaf == dist["leafRows"]
        # the broker-local mirror now reports its fan-out too (satellite)
        assert local["joinFanout"] == 1

    def test_trace_merges_per_server_spans(self, cluster):
        _, _, _, broker, _ = cluster
        resp = broker.execute(
            f"SET joinStrategy = 'distributed'; SET trace = true; "
            f"{GROUP_SQL}")
        assert not resp.get("exceptions"), resp.get("exceptions")
        ti = resp.get("traceInfo") or {}
        assert {"stage2:server_0", "stage2:server_1"} <= set(ti)

    def test_spill_path_stays_bit_exact(self, cluster):
        _, _, servers, broker, con = cluster
        oracle = [[_norm(v) for v in r] for r in con.execute(GROUP_SQL)]
        limits = [s.exchanges.spill_limit_bytes for s in servers]
        for s in servers:
            s.exchanges.spill_limit_bytes = 512
        try:
            dist = broker.execute(
                f"SET joinStrategy = 'distributed'; {GROUP_SQL}")
        finally:
            for s, lim in zip(servers, limits):
                s.exchanges.spill_limit_bytes = lim
        assert _rows(dist) == oracle
        assert dist["exchangeSpillCount"] > 0

    def test_demotion_past_broadcast_cap(self, cluster, monkeypatch):
        """An unforced SHUFFLE plan whose build side exceeds the
        broadcast cap (per registry doc counts) demotes to DISTRIBUTED
        at runtime; querylog/template_key see the mutated strategy."""
        from pinot_tpu.query2 import logical

        _, _, _, broker, con = cluster
        monkeypatch.setattr(logical, "BROADCAST_MAX_BUILD_ROWS", 100)
        resp = broker.execute(GROUP_SQL)
        assert resp["joinStrategy"] == "DISTRIBUTED"
        assert resp.get("joinStrategyDemoted") is True
        assert _rows(resp) == [[_norm(v) for v in r]
                               for r in con.execute(GROUP_SQL)]

    def test_forced_but_unroutable_falls_back_local(self, tmp_path):
        """SET joinStrategy='distributed' against an embedded engine (no
        fleet at all) must still answer — through the broker-local
        SHUFFLE mirror — and report the EFFECTIVE strategy."""
        from pinot_tpu.engine.engine import QueryEngine

        fact, ship = _data()
        fact_schema, ship_schema = _schemas()
        eng = QueryEngine(device_executor=None)
        eng.add_segment("fa", build_segment(
            fact_schema, fact, str(tmp_path / "fa"),
            TableConfig(table_name="fa"), "fa0"))
        eng.add_segment("fb", build_segment(
            ship_schema, ship, str(tmp_path / "fb"),
            TableConfig(table_name="fb"), "fb0"))
        local = eng.execute(f"SET joinStrategy = 'shuffle'; {GROUP_SQL}")
        dist = eng.execute(f"SET joinStrategy = 'distributed'; {GROUP_SQL}")
        assert _rows(dist) == _rows(local)
        assert dist["joinStrategy"] == "SHUFFLE"  # what actually ran


class TestDistributedExplain:
    def test_explain_renders_distributed_boundary(self, cluster):
        _, _, _, broker, _ = cluster
        resp = broker.execute(
            f"SET joinStrategy = 'distributed'; EXPLAIN PLAN FOR "
            f"{GROUP_SQL}")
        text = "\n".join(r[0] for r in resp["resultTable"]["rows"])
        assert "STAGE_BOUNDARY(exchange:DISTRIBUTED [server-fleet])" \
            in text
        assert "strategy=DISTRIBUTED" in text

    def test_explain_analyze_exchange_actuals(self, cluster):
        _, _, _, broker, _ = cluster
        resp = broker.execute(
            f"SET joinStrategy = 'distributed'; EXPLAIN ANALYZE "
            f"{GROUP_SQL}")
        assert not resp.get("exceptions"), resp.get("exceptions")
        text = "\n".join(r[0] for r in resp["resultTable"]["rows"])
        boundary = next(ln for ln in text.splitlines()
                        if "STAGE_BOUNDARY" in ln)
        assert "exchange:DISTRIBUTED" in boundary
        assert "partitions=4" in boundary
        assert "shippedBytes=" in boundary
        assert "spills=" in boundary
        assert "stage2Rows[" in boundary
        assert "server_0=" in boundary and "server_1=" in boundary


class TestDistributedChaos:
    def test_error_faults_retry_on_replica(self, cluster):
        """Kill every transfer addressed to server_1: attempt 1 answers
        a typed EXCHANGE_TRANSFER_FAILED naming the peer, the retry
        excludes server_1 and completes bit-exact on the replicas."""
        _, _, _, broker, con = cluster
        _reset_failures(broker)
        f = faults.install(faults.Fault(
            point="exchange.transfer", target="server_1", mode="error"))
        try:
            resp = broker.execute(
                f"SET joinStrategy = 'distributed'; {GROUP_SQL}")
        finally:
            faults.clear()
            _reset_failures(broker)
        assert _rows(resp) == [[_norm(v) for v in r]
                               for r in con.execute(GROUP_SQL)]
        assert resp["numRetries"] == 1
        assert f.fired > 0
        assert set(resp["exchange"]["servers"]) == {"server_0"}

    def test_blackhole_bounded_by_deadline(self, cluster):
        """A blackholed receiver must not hang the query: the sender's
        injected stall is bounded by the stage deadline, the failure
        comes back typed, and the retry (or typed partial) lands inside
        the query budget."""
        _, _, _, broker, con = cluster
        _reset_failures(broker)
        faults.install(faults.Fault(
            point="exchange.transfer", target="server_1",
            mode="blackhole"))
        t0 = time.time()
        try:
            resp = broker.execute(
                f"SET joinStrategy = 'distributed'; "
                f"SET timeoutMs = 4000; {GROUP_SQL}")
        finally:
            faults.clear()
            _reset_failures(broker)
        wall = time.time() - t0
        assert wall < 8.0, wall
        if resp.get("exceptions"):
            assert resp.get("partialResult") is True
        else:
            assert _rows(resp) == [[_norm(v) for v in r]
                                   for r in con.execute(GROUP_SQL)]
            assert resp["numRetries"] == 1

    def test_unrecoverable_returns_typed_partial(self, cluster, caplog):
        """Faults on EVERY instance: no replica can cover the exchange —
        the broker answers a typed partialResult inside the deadline
        instead of hanging or retrying forever."""
        import logging

        _, _, _, broker, con = cluster
        _reset_failures(broker)
        faults.install(faults.Fault(point="exchange.transfer",
                                    mode="error"))
        t0 = time.time()
        try:
            with caplog.at_level(logging.CRITICAL,
                                 logger="pinot_tpu.broker.broker"):
                resp = broker.execute(
                    f"SET joinStrategy = 'distributed'; "
                    f"SET timeoutMs = 5000; {GROUP_SQL}")
        finally:
            faults.clear()
            _reset_failures(broker)
        assert time.time() - t0 < 6.0
        assert resp.get("partialResult") is True
        excs = resp.get("exceptions")
        assert excs and "distributed stage-2 failed" in excs[0]["message"]
        # the fleet answers normally once the faults clear
        ok = broker.execute(f"SET joinStrategy = 'distributed'; "
                            f"{GROUP_SQL}")
        assert _rows(ok) == [[_norm(v) for v in r]
                             for r in con.execute(GROUP_SQL)]


class TestDistributedConsuming:
    def test_sealed_plus_consuming_parity(self, cluster):
        """A realtime table mid-consumption joins distributed against a
        sealed fact table bit-exactly: consuming chunklets ride the same
        routed-segment scan as sealed segments."""
        from pinot_tpu.stream.memory_stream import TopicRegistry

        registry, controller, servers, broker, con = cluster
        _reset_failures(broker)
        TopicRegistry.delete("exch_clicks")
        topic = TopicRegistry.create("exch_clicks", 1)
        schema = Schema.build(
            name="rt",
            dimensions=[("k3", DataType.INT)],
            metrics=[("n", DataType.INT)],
        )
        cfg = TableConfig(
            table_name="rt", table_type=TableType.REALTIME, replication=2,
            stream=StreamConfig(
                stream_type="memory", topic="exch_clicks", decoder="json",
                segment_flush_threshold_rows=100000,
                segment_flush_threshold_seconds=3600,
            ),
        )
        controller.add_table(cfg, schema)
        rng = np.random.default_rng(3)
        keys = rng.integers(0, N_KEYS, 300)
        vals = rng.integers(1, 20, 300)
        for k, n in zip(keys.tolist(), vals.tolist()):
            topic.publish_json({"k3": k, "n": n})

        def _count():
            r = broker.execute("SELECT COUNT(*) FROM rt")
            if r.get("exceptions"):
                return -1
            return r["resultTable"]["rows"][0][0]

        assert _wait_until(lambda: _count() == 300, timeout=20), _count()
        con.execute("CREATE TABLE rt (k3 INT, n INT)")
        con.executemany("INSERT INTO rt VALUES (?,?)",
                        list(zip(keys.tolist(), vals.tolist())))
        sql = ("SELECT r.k3, COUNT(*), SUM(a.v), SUM(r.n) FROM fa a "
               "JOIN rt r ON a.k = r.k3 WHERE a.status = 'paid' "
               "GROUP BY r.k3 ORDER BY r.k3 LIMIT 25")
        oracle = [[_norm(v) for v in r] for r in con.execute(sql)]
        local = broker.execute(f"SET joinStrategy = 'shuffle'; {sql}")
        dist = broker.execute(f"SET joinStrategy = 'distributed'; {sql}")
        assert _rows(local) == oracle
        assert _rows(dist) == oracle
        assert dist["joinStrategy"] == "DISTRIBUTED"


class TestDistributedMesh:
    @pytest.fixture(scope="class")
    def mesh_cluster(self, tmp_path_factory):
        from pinot_tpu.engine.device import DeviceExecutor
        from pinot_tpu.parallel.mesh import make_mesh

        tmp = tmp_path_factory.mktemp("exchange_mesh")
        devs = [DeviceExecutor(mesh=make_mesh(8)), None]
        registry, controller, servers, broker, con = \
            _make_cluster(tmp, device_executors=devs)
        yield broker, con
        broker.close()
        for s in servers:
            s.stop()

    def test_mesh_and_host_workers_agree(self, mesh_cluster):
        """One mesh-device worker + one host worker in the same
        exchange: integer stage-2 partials are exact on both backends,
        so the merged answer matches the oracle bit-for-bit."""
        broker, con = mesh_cluster
        oracle = [[_norm(v) for v in r] for r in con.execute(GROUP_SQL)]
        dist = broker.execute(
            f"SET joinStrategy = 'distributed'; {GROUP_SQL}")
        assert _rows(dist) == oracle
        assert dist["joinStrategy"] == "DISTRIBUTED"
        assert dist["numServersQueried"] == 2


class TestExchangePrimitives:
    def test_stable_hash_dtype_independent(self):
        from pinot_tpu.query2 import exchange

        a = np.array([1, 2, 3, 1 << 40], dtype=np.int64)
        b = a.astype(np.float64)
        ha = exchange.stable_hash64([a], 4)
        hb = exchange.stable_hash64([b], 4)
        assert (ha == hb).all()
        assert (ha >= 0).all()
        # strings hash by value too
        s1 = np.array(["x", "y", "x"], dtype=object)
        s2 = np.array(["x", "y", "x"])
        assert (exchange.stable_hash64([s1], 3)
                == exchange.stable_hash64([s2], 3)).all()

    def test_wire_roundtrip_empty_partition_keeps_dtype(self):
        from pinot_tpu.query2 import exchange

        cols = {"k": np.empty(0, dtype=np.int64),
                "s": np.empty(0, dtype="U1")}
        payload = exchange.encode_transfer("e1", "s0", "a", 3, cols, 0)
        msg = exchange.decode_transfer(payload)
        assert msg["n"] == 0 and msg["partition"] == 3
        assert msg["cols"]["k"].dtype == np.int64
        assert msg["cols"]["k"].shape == (0,)

    def test_buffer_spills_and_gathers_in_order(self, tmp_path):
        from pinot_tpu.query2 import exchange

        buf = exchange.ExchangeBuffer("e2", str(tmp_path / "spill"),
                                      spill_limit_bytes=64)
        buf.offer("s0", "a", 0, {"v": np.arange(50, dtype=np.int64)}, 50)
        buf.offer("s1", "a", 0, {"v": np.arange(50, 80,
                                                dtype=np.int64)}, 30)
        assert buf.spill_count > 0
        buf.mark_done("s0", {"a": {"0": 1}})
        buf.mark_done("s1", {"a": {"0": 1}})

        class _NoDeadline:
            def remaining_s(self):
                return 5.0

            def check(self, where=None):
                return None

        buf.wait_ready(["s0", "s1"], _NoDeadline())
        cols, n = buf.gather("a", 0)
        assert n == 80
        got = np.sort(np.asarray(cols["v"]))
        assert (got == np.arange(80)).all()
        buf.close()
