"""Index-served filter paths: sorted-column binary search, inverted-index
doc lists, and their equivalence with the full-scan path.

Reference analogs: SortedIndexBasedFilterOperator, BitmapBasedFilterOperator,
and the index-priority ordering in FilterOperatorUtils.java:165-194.
"""

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.engine.host import filter_operator_for
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    """Two segments: one sorted by `k`, one unsorted with an inverted index
    on `v`."""
    base = tmp_path_factory.mktemp("fidx")
    schema = Schema.build(
        name="t",
        dimensions=[("k", DataType.INT), ("v", DataType.STRING)],
        metrics=[("m", DataType.INT)],
    )
    cfg = TableConfig(
        table_name="t",
        indexing=IndexingConfig(inverted_index_columns=["v"]),
    )
    rng = np.random.default_rng(9)
    n = 20_000
    sorted_cols = {
        "k": np.sort(rng.integers(0, 500, n)).astype(np.int32),
        "v": np.array([f"s{j:02d}" for j in rng.integers(0, 40, n)]),
        "m": rng.integers(0, 100, n).astype(np.int32),
    }
    unsorted_cols = {
        "k": rng.integers(0, 500, n).astype(np.int32),
        "v": np.array([f"s{j:02d}" for j in rng.integers(0, 40, n)]),
        "m": rng.integers(0, 100, n).astype(np.int32),
    }
    build_segment(schema, sorted_cols, str(base / "sorted"), cfg, "sorted")
    build_segment(schema, unsorted_cols, str(base / "unsorted"), cfg, "unsorted")
    return (
        ImmutableSegment(str(base / "sorted")),
        ImmutableSegment(str(base / "unsorted")),
        sorted_cols,
        unsorted_cols,
    )


def _engine(seg):
    eng = QueryEngine(device_executor=None)
    eng.add_segment("t", seg)
    return eng


class TestOperatorChoice:
    def test_sorted_beats_inverted(self, segs):
        s_sorted, s_unsorted, *_ = segs
        from pinot_tpu.sql.compiler import compile_query

        q = compile_query("SELECT COUNT(*) FROM t WHERE k = 7")
        assert s_sorted.column_metadata("k").is_sorted
        assert filter_operator_for(s_sorted, q.filter.predicate) == "SORTED_INDEX"
        assert filter_operator_for(s_unsorted, q.filter.predicate) == "FULL_SCAN"

        qv = compile_query("SELECT COUNT(*) FROM t WHERE v = 's01'")
        assert filter_operator_for(s_unsorted, qv.filter.predicate) == "INVERTED_INDEX"

    def test_explain_shows_index_operator(self, segs):
        _, s_unsorted, *_ = segs
        eng = _engine(s_unsorted)
        r = eng.execute("EXPLAIN PLAN FOR SELECT COUNT(*) FROM t WHERE v = 's01'")
        ops = [row[0] for row in r["resultTable"]["rows"]]
        assert any("FILTER_INVERTED_INDEX" in o for o in ops), ops


class TestIndexEqualsScan:
    @pytest.mark.parametrize(
        "where",
        [
            "k = 7",
            "k BETWEEN 100 AND 200",
            "k IN (3, 99, 471)",
            "v = 's05'",
            "v IN ('s01', 's17', 's39')",
            "v BETWEEN 's10' AND 's20'",
            "k > 490 AND v = 's00'",
            "NOT v = 's01'",
        ],
    )
    def test_results_match_numpy(self, segs, where):
        s_sorted, s_unsorted, sc, uc = segs
        for seg, cols in ((s_sorted, sc), (s_unsorted, uc)):
            eng = _engine(seg)
            r = eng.execute(f"SELECT COUNT(*), SUM(m) FROM t WHERE {where}")
            assert not r.get("exceptions"), r
            mask = _numpy_mask(cols, where)
            got = r["resultTable"]["rows"][0]
            assert got[0] == int(mask.sum()), (where, seg.name)
            if mask.any():
                assert got[1] == int(cols["m"][mask].sum()), (where, seg.name)

    def test_zero_entries_scanned_for_index_filter(self, segs):
        s_sorted, s_unsorted, *_ = segs
        r = _engine(s_sorted).execute("SELECT COUNT(*) FROM t WHERE k = 7")
        assert r["numEntriesScannedInFilter"] == 0
        r = _engine(s_unsorted).execute("SELECT COUNT(*) FROM t WHERE v = 's01'")
        assert r["numEntriesScannedInFilter"] == 0
        # scan predicates still count
        r = _engine(s_unsorted).execute("SELECT COUNT(*) FROM t WHERE k = 7")
        assert r["numEntriesScannedInFilter"] == s_unsorted.n_docs


class TestRawRangeIndex:
    """Sorted-projection range index on RAW (no-dictionary) columns
    (RangeIndexCreator / BitSlicedRangeIndexReader analog)."""

    @pytest.fixture(scope="class")
    def rseg(self, tmp_path_factory):
        base = tmp_path_factory.mktemp("ridx")
        schema = Schema.build(
            name="r",
            dimensions=[("k", DataType.INT)],
            metrics=[("price", DataType.DOUBLE), ("qty", DataType.INT)],
        )
        cfg = TableConfig(
            table_name="r",
            indexing=IndexingConfig(range_index_columns=["price"],
                                    no_dictionary_columns=["price"]),
        )
        rng = np.random.default_rng(3)
        cols = {
            "k": rng.integers(0, 50, 10_000).astype(np.int32),
            "price": np.round(rng.uniform(0, 1000, 10_000), 2),
            "qty": rng.integers(0, 9, 10_000).astype(np.int32),
        }
        build_segment(schema, cols, str(base / "seg"), cfg, "seg")
        return ImmutableSegment(str(base / "seg")), cols

    @staticmethod
    def _rengine(seg):
        eng = QueryEngine(device_executor=None)
        eng.add_segment("r", seg)
        return eng

    def test_operator_choice_and_files(self, rseg):
        seg, _ = rseg
        assert seg.column_metadata("price").has_range
        assert seg.range_index("price") is not None
        from pinot_tpu.query.context import Expression, Predicate, PredicateType

        p = Predicate(PredicateType.RANGE, Expression.identifier("price"),
                      lower=10.0, upper=20.0, lower_inclusive=True,
                      upper_inclusive=True)
        assert filter_operator_for(seg, p) == "RANGE_INDEX"

    @pytest.mark.parametrize("where,mask_fn", [
        ("price > 900", lambda c: c["price"] > 900),
        ("price BETWEEN 100 AND 101.5",
         lambda c: (c["price"] >= 100) & (c["price"] <= 101.5)),
        ("price <= 0.5", lambda c: c["price"] <= 0.5),
        ("price = 500.0", lambda c: c["price"] == 500.0),
        ("price >= 999 AND qty > 3",
         lambda c: (c["price"] >= 999) & (c["qty"] > 3)),
    ])
    def test_matches_scan(self, rseg, where, mask_fn):
        seg, cols = rseg
        r = self._rengine(seg).execute(f"SELECT COUNT(*), SUM(qty) FROM r WHERE {where}")
        assert not r.get("exceptions"), r
        mask = mask_fn(cols)
        assert r["resultTable"]["rows"][0][0] == int(mask.sum()), where
        if mask.any():
            assert r["resultTable"]["rows"][0][1] == int(cols["qty"][mask].sum())

    def test_zero_entries_scanned(self, rseg):
        seg, _ = rseg
        r = self._rengine(seg).execute("SELECT COUNT(*) FROM r WHERE price > 990")
        assert r["numEntriesScannedInFilter"] == 0


def _numpy_mask(cols, where):
    k, v = cols["k"], cols["v"]
    masks = {
        "k = 7": k == 7,
        "k BETWEEN 100 AND 200": (k >= 100) & (k <= 200),
        "k IN (3, 99, 471)": np.isin(k, [3, 99, 471]),
        "v = 's05'": v == "s05",
        "v IN ('s01', 's17', 's39')": np.isin(v, ["s01", "s17", "s39"]),
        "v BETWEEN 's10' AND 's20'": (v >= "s10") & (v <= "s20"),
        "k > 490 AND v = 's00'": (k > 490) & (v == "s00"),
        "NOT v = 's01'": v != "s01",
    }
    return masks[where]
