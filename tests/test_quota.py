"""Broker per-table query quota (queryquota/ analog)."""

import time

import numpy as np

from pinot_tpu.broker.broker import Broker
from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import QuotaConfig, TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment


def wait_until(cond, timeout=15.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_quota_rejects_above_rate_and_refills(tmp_path):
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "ds"))
    server = ServerInstance("s0", registry, str(tmp_path / "sd"),
                            device_executor=None)
    server.start()
    broker = Broker(registry, timeout_s=10.0)
    try:
        schema = Schema.build(name="limited",
                              dimensions=[("k", DataType.STRING)],
                              metrics=[("v", DataType.LONG)])
        cfg = TableConfig(table_name="limited",
                          quota=QuotaConfig(max_queries_per_second=2))
        controller.add_table(cfg, schema)
        build_segment(schema, {"k": np.array(["a"]), "v": np.array([1])},
                      str(tmp_path / "up"), cfg, "s0seg")
        controller.upload_segment("limited", str(tmp_path / "up"))
        assert wait_until(
            lambda: len(registry.external_view("limited_OFFLINE")) == 1)

        sql = "SELECT COUNT(*) FROM limited"
        ok = [broker.execute(sql) for _ in range(2)]
        assert all(not r.get("exceptions") for r in ok), ok
        rejected = broker.execute(sql)
        assert rejected["exceptions"][0]["errorCode"] == 429

        time.sleep(1.1)  # bucket refills at 2 tokens/s
        again = broker.execute(sql)
        assert not again.get("exceptions"), again
    finally:
        broker.close()
        server.stop()


def test_typed_table_name_shares_bucket(tmp_path):
    """'limited' and 'limited_OFFLINE' draw from ONE bucket (r3 review:
    suffixing the name must not multiply the quota)."""
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "ds"))
    server = ServerInstance("s0", registry, str(tmp_path / "sd"),
                            device_executor=None)
    server.start()
    broker = Broker(registry, timeout_s=10.0)
    try:
        schema = Schema.build(name="limited",
                              dimensions=[("k", DataType.STRING)],
                              metrics=[("v", DataType.LONG)])
        cfg = TableConfig(table_name="limited",
                          quota=QuotaConfig(max_queries_per_second=2))
        controller.add_table(cfg, schema)
        build_segment(schema, {"k": np.array(["a"]), "v": np.array([1])},
                      str(tmp_path / "up"), cfg, "s0seg")
        controller.upload_segment("limited", str(tmp_path / "up"))
        assert wait_until(
            lambda: len(registry.external_view("limited_OFFLINE")) == 1)
        assert not broker.execute(
            "SELECT COUNT(*) FROM limited").get("exceptions")
        assert not broker.execute(
            "SELECT COUNT(*) FROM limited_OFFLINE").get("exceptions")
        r = broker.execute("SELECT COUNT(*) FROM limited_OFFLINE")
        assert r["exceptions"][0]["errorCode"] == 429
    finally:
        broker.close()
        server.stop()


def test_non_positive_quota_rejected_at_config():
    import pytest

    with pytest.raises(ValueError, match="positive"):
        TableConfig(table_name="t",
                    quota=QuotaConfig(max_queries_per_second=0))


def test_timeout_ms_query_option(tmp_path):
    """SET timeoutMs overrides the broker's per-query fan-out timeout
    (the reference's timeoutMs query option)."""
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "ds"))
    server = ServerInstance("s0", registry, str(tmp_path / "sd"),
                            device_executor=None)
    server.start()
    broker = Broker(registry, timeout_s=10.0)
    try:
        schema = Schema.build(name="t", dimensions=[("k", DataType.STRING)],
                              metrics=[("v", DataType.LONG)])
        cfg = TableConfig(table_name="t")
        controller.add_table(cfg, schema)
        build_segment(schema, {"k": np.array(["a"]), "v": np.array([1])},
                      str(tmp_path / "up"), cfg, "s0seg")
        controller.upload_segment("t", str(tmp_path / "up"))
        assert wait_until(
            lambda: len(registry.external_view("t_OFFLINE")) == 1)
        from pinot_tpu.transport.grpc_transport import QueryRouterChannel

        seen = []
        real_submit = QueryRouterChannel.submit

        def recording(self, payload, timeout):
            import json as _json

            seen.append((timeout, _json.loads(payload.decode())["timeoutMs"]))
            return real_submit(self, payload, timeout)

        QueryRouterChannel.submit = recording
        try:
            ok = broker.execute("SET timeoutMs = 2500; SELECT COUNT(*) FROM t")
            assert not ok.get("exceptions"), ok
            # deadline propagation: the wire carries the REMAINING budget
            # (<= the SET value; > 0 minus routing overhead) and the RPC
            # deadline is that budget plus a small grace so the server's
            # own typed QUERY_TIMEOUT answers first
            rpc_timeout, budget_ms = seen[-1]
            assert 2000.0 < budget_ms <= 2500.0, seen
            assert abs(rpc_timeout - (budget_ms / 1e3 + 0.25)) < 1e-6, seen
            ok = broker.execute("SELECT COUNT(*) FROM t")
            rpc_timeout, budget_ms = seen[-1]
            assert 9500.0 < budget_ms <= 10000.0  # broker default budget
        finally:
            QueryRouterChannel.submit = real_submit
    finally:
        broker.close()
        server.stop()


def test_no_quota_config_unlimited(tmp_path):
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "ds"))
    server = ServerInstance("s0", registry, str(tmp_path / "sd"),
                            device_executor=None)
    server.start()
    broker = Broker(registry, timeout_s=10.0)
    try:
        schema = Schema.build(name="free", dimensions=[("k", DataType.STRING)],
                              metrics=[("v", DataType.LONG)])
        cfg = TableConfig(table_name="free")
        controller.add_table(cfg, schema)
        build_segment(schema, {"k": np.array(["a"]), "v": np.array([1])},
                      str(tmp_path / "up"), cfg, "s0seg")
        controller.upload_segment("free", str(tmp_path / "up"))
        assert wait_until(
            lambda: len(registry.external_view("free_OFFLINE")) == 1)
        rs = [broker.execute("SELECT COUNT(*) FROM free") for _ in range(20)]
        assert all(not r.get("exceptions") for r in rs)
    finally:
        broker.close()
        server.stop()
