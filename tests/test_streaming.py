"""Streaming selection execution: block-wise server results + broker
short-circuit.

Reference analogs: server.proto streaming Submit + streaming operators +
StreamingReduceService — selection queries flow as per-segment DataTable
blocks, the broker cancels once it has offset+limit rows, and the server
stops executing segments past its row budget.
"""

import time

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment


def wait_until(cond, timeout=15.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


N_SEGMENTS = 6
ROWS = 1000


@pytest.fixture()
def cluster(tmp_path):
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "ds"))
    server = ServerInstance("server_0", registry, str(tmp_path / "s0"),
                            device_executor=None)
    server.start()
    broker = Broker(registry, timeout_s=10.0)
    schema = Schema.build(
        name="ev",
        dimensions=[("kind", DataType.STRING)],
        metrics=[("v", DataType.LONG)],
    )
    cfg = TableConfig(table_name="ev", replication=1)
    controller.add_table(cfg, schema)
    rng = np.random.default_rng(1)
    valid = set()
    for i in range(N_SEGMENTS):
        cols = {
            "kind": np.array(["a", "b", "c"])[rng.integers(0, 3, ROWS)],
            "v": rng.integers(0, 10_000, ROWS).astype(np.int64),
        }
        for k, v in zip(cols["kind"], cols["v"]):
            valid.add((k, int(v)))
        d = str(tmp_path / f"up{i}")
        build_segment(schema, cols, d, cfg, f"ev_{i}")
        controller.upload_segment("ev", d)
    assert wait_until(
        lambda: len(registry.external_view("ev_OFFLINE")) == N_SEGMENTS)
    yield registry, controller, server, broker, valid
    broker.close()
    server.stop()


class TestStreamingSelection:
    def test_rows_valid_and_limit_honored(self, cluster):
        registry, controller, server, broker, valid = cluster
        r = broker.execute("SELECT kind, v FROM ev LIMIT 25")
        assert not r.get("exceptions"), r
        rows = r["resultTable"]["rows"]
        assert len(rows) == 25
        assert all((k, v) in valid for k, v in rows)

    def test_server_stops_at_row_budget(self, cluster):
        registry, controller, server, broker, valid = cluster
        r = broker.execute("SELECT kind, v FROM ev LIMIT 10")
        assert not r.get("exceptions"), r
        # one 1000-row segment covers LIMIT 10: the server's budget stops
        # execution after the first block (5 segments never touched)
        assert r["numSegmentsProcessed"] == 1
        assert r["numDocsScanned"] <= ROWS

    def test_streaming_off_matches(self, cluster):
        registry, controller, server, broker, valid = cluster
        r = broker.execute("SET streaming = false; SELECT kind, v FROM ev LIMIT 25")
        assert not r.get("exceptions"), r
        rows = r["resultTable"]["rows"]
        assert len(rows) == 25
        assert all((k, v) in valid for k, v in rows)
        # unary path executes everything it was asked for
        assert r["numSegmentsProcessed"] == N_SEGMENTS

    def test_filtered_streaming(self, cluster):
        registry, controller, server, broker, valid = cluster
        r = broker.execute("SELECT kind, v FROM ev WHERE kind = 'a' LIMIT 5000")
        assert not r.get("exceptions"), r
        rows = r["resultTable"]["rows"]
        n_a = sum(1 for k, _ in valid if k == "a")
        # kind='a' appears ~1/3 of 6000 rows with duplicates collapsed in
        # the oracle set; compare against the actual scan
        assert all(k == "a" for k, _ in rows)
        assert len(rows) >= min(n_a, 1)  # non-empty, all filtered

    def test_order_by_takes_unary_path(self, cluster):
        registry, controller, server, broker, valid = cluster
        r = broker.execute("SELECT kind, v FROM ev ORDER BY v DESC LIMIT 5")
        assert not r.get("exceptions"), r
        vs = [row[1] for row in r["resultTable"]["rows"]]
        assert vs == sorted(vs, reverse=True)
        top = sorted((v for _, v in valid), reverse=True)[0]
        assert vs[0] == top

    def test_stats_match_unary_semantics(self, cluster):
        registry, controller, server, broker, valid = cluster
        r = broker.execute("SELECT kind, v FROM ev LIMIT 10")
        r2 = broker.execute("SET streaming = false; SELECT kind, v FROM ev LIMIT 10")
        # totalDocs covers every requested segment on BOTH paths
        assert r["totalDocs"] == r2["totalDocs"] == N_SEGMENTS * ROWS
        assert r["numSegmentsQueried"] == N_SEGMENTS
        # one server, regardless of how many blocks it streamed
        assert r["numServersResponded"] == 1

    def test_hybrid_time_boundary_respected_when_streaming(self, cluster, tmp_path):
        """The time-boundary predicate must apply on the streaming path or
        hybrid overlap rows double-read."""
        from pinot_tpu.common.table_config import StreamConfig, TableType
        from pinot_tpu.stream.memory_stream import TopicRegistry

        registry, controller, server, broker, _ = cluster
        schema = Schema.build(
            name="metr",
            dimensions=[("h", DataType.STRING)],
            metrics=[("v", DataType.INT)],
            datetimes=[("ts", DataType.LONG)],
        )
        off_cfg = TableConfig(table_name="metr", time_column="ts")
        controller.add_table(off_cfg, schema)
        d = str(tmp_path / "metr_off")
        build_segment(
            schema,
            {"h": ["x"] * 100, "v": [1] * 100, "ts": list(range(100))},
            d, off_cfg, "metr_0")
        controller.upload_segment("metr", d)
        TopicRegistry.delete("metr_s")
        topic = TopicRegistry.create("metr_s", 1)
        rt_cfg = TableConfig(
            table_name="metr", table_type=TableType.REALTIME, time_column="ts",
            stream=StreamConfig(stream_type="memory", topic="metr_s",
                                decoder="json",
                                segment_flush_threshold_rows=10_000,
                                segment_flush_threshold_seconds=3600))
        controller.add_table(rt_cfg, schema)
        for ts in range(80, 150):  # overlaps offline 80..99
            topic.publish_json({"h": "x", "v": 1, "ts": ts})

        def total():
            r = broker.execute("SELECT ts FROM metr LIMIT 10000")
            if r.get("exceptions"):
                return -1
            return len(r["resultTable"]["rows"])

        assert wait_until(lambda: total() == 150), total()

    def test_streaming_error_in_band(self, cluster):
        registry, controller, server, broker, valid = cluster
        r = broker.execute("SELECT nosuchcol FROM ev LIMIT 5")
        assert r.get("exceptions"), r
        assert "SERVER_NOT_RESPONDING" not in r["exceptions"][0]["message"]
