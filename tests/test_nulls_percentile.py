"""Null-value vectors + mergeable percentile digest.

Reference analogs: NullValueVectorReaderImpl + IS_NULL predicate
evaluation, PercentileTDigestAggregationFunction's bounded mergeable
state with error-bounded estimates.
"""

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.ops import quantile_digest as qd
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.mutable import MutableSegment
from pinot_tpu.storage.segment import ImmutableSegment


SCHEMA = Schema.build(
    name="t",
    dimensions=[("k", DataType.STRING)],
    metrics=[("v", DataType.LONG), ("f", DataType.DOUBLE)],
)


def _engine_with(seg):
    engine = QueryEngine(device_executor=None)
    engine.add_segment("t", seg)
    return engine


def _rows(engine, sql):
    r = engine.execute(sql)
    assert not r.get("exceptions"), r
    return r["resultTable"]["rows"]


class TestNullVectors:
    def _seg(self, tmp_path):
        cols = {
            "k": ["a", None, "b", None, "c"],
            "v": [1, 2, None, 4, None],
            "f": [1.0, 2.0, 3.0, 4.0, 5.0],
        }
        return build_segment(SCHEMA, cols, str(tmp_path / "s"),
                             TableConfig(table_name="t"), "s0")

    def test_nullvec_written_and_read(self, tmp_path):
        seg = self._seg(tmp_path)
        assert seg.column_metadata("k").has_null_vector
        assert seg.column_metadata("v").has_null_vector
        assert not seg.column_metadata("f").has_null_vector
        assert seg.null_vector("k").tolist() == [False, True, False, True, False]
        assert seg.null_vector("v").tolist() == [False, False, True, False, True]
        assert seg.null_vector("f") is None
        # forward index stores substituted defaults
        assert seg.values("k")[1] == DataType.STRING.default_null
        # metric null defaults are ZERO (reference
        # DEFAULT_METRIC_NULL_VALUE_OF_LONG), dimensions use the sentinel
        assert int(seg.values("v")[2]) == 0

    def test_is_null_predicates(self, tmp_path):
        engine = _engine_with(self._seg(tmp_path))
        assert _rows(engine, "SELECT COUNT(*) FROM t WHERE k IS NULL") == [[2]]
        assert _rows(engine, "SELECT COUNT(*) FROM t WHERE k IS NOT NULL") == [[3]]
        assert _rows(engine, "SELECT COUNT(*) FROM t WHERE v IS NULL") == [[2]]
        assert _rows(engine, "SELECT COUNT(*) FROM t WHERE f IS NULL") == [[0]]
        assert _rows(engine,
                     "SELECT COUNT(*) FROM t WHERE k IS NULL AND v IS NULL"
                     ) == [[0]]
        assert _rows(engine,
                     "SELECT SUM(f) FROM t WHERE v IS NOT NULL") == [[7.0]]

    def test_segment_reload_preserves_nulls(self, tmp_path):
        self._seg(tmp_path)
        seg = ImmutableSegment(str(tmp_path / "s"))
        assert seg.null_vector("k").tolist() == [False, True, False, True, False]

    def test_mutable_nulls_and_seal(self, tmp_path):
        ms = MutableSegment(SCHEMA, "m0", TableConfig(table_name="t"))
        for row in ({"k": "a", "v": 1, "f": 0.5}, {"k": None, "v": None, "f": 1.5},
                    {"v": 3, "f": 2.5}):  # missing key counts as null too
            ms.index(row)
        assert ms.null_vector("k").tolist() == [False, True, True]
        assert ms.null_vector("v").tolist() == [False, True, False]
        assert ms.null_vector("f") is None
        engine = _engine_with(ms)
        assert _rows(engine, "SELECT COUNT(*) FROM t WHERE k IS NULL") == [[2]]
        sealed = ms.seal(str(tmp_path / "sealed"))
        assert sealed.null_vector("k").tolist() == [False, True, True]
        engine2 = _engine_with(sealed)
        assert _rows(engine2, "SELECT COUNT(*) FROM t WHERE v IS NULL") == [[1]]

    def test_star_tree_not_used_for_null_predicates(self, tmp_path):
        from pinot_tpu.common.table_config import (
            IndexingConfig,
            StarTreeIndexConfig,
        )

        cfg = TableConfig(
            table_name="t",
            indexing=IndexingConfig(
                star_tree_configs=[StarTreeIndexConfig(
                    dimensions_split_order=["k"],
                    function_column_pairs=["COUNT__*", "SUM__v"],
                )]),
        )
        cols = {"k": ["a", None, "a", "b"], "v": [1, 2, 3, None],
                "f": [0.0, 0.0, 0.0, 0.0]}
        seg = build_segment(SCHEMA, cols, str(tmp_path / "st"), cfg, "st0")
        engine = _engine_with(seg)
        # the tree sees substituted defaults; IS_NULL must bypass it
        assert _rows(engine, "SELECT COUNT(*) FROM t WHERE k IS NULL") == [[1]]
        assert _rows(engine, "SELECT SUM(v) FROM t WHERE v IS NOT NULL") == [[6]]


class TestQuantileDigest:
    @pytest.mark.parametrize("dist", ["uniform", "normal", "lognormal"])
    def test_rank_error_bounded(self, dist):
        rng = np.random.default_rng(11)
        n = 50_000
        vals = {
            "uniform": rng.uniform(0, 1000, n),
            "normal": rng.normal(500, 100, n),
            "lognormal": rng.lognormal(3, 1, n),
        }[dist]
        # fold in three chunks + merge (the distributed path)
        m = w = np.empty(0)
        digests = []
        for chunk in np.array_split(vals, 3):
            digests.append(qd.add_values([], [], chunk))
        m, w = digests[0]
        for m2, w2 in digests[1:]:
            m, w = qd.merge(m, w, m2, w2)
        assert len(m) <= 2 * qd.DEFAULT_COMPRESSION
        s = np.sort(vals)
        for q in (0.01, 0.25, 0.5, 0.75, 0.9, 0.99):
            est = qd.quantile(m, w, q)
            rank = np.searchsorted(s, est) / n
            assert abs(rank - q) <= 0.015, (dist, q, est, rank)

    def test_empty_and_single(self):
        assert np.isnan(qd.quantile([], [], 0.5))
        m, w = qd.add_values([], [], [42.0])
        assert qd.quantile(m, w, 0.0) == 42.0
        assert qd.quantile(m, w, 1.0) == 42.0

    def test_group_by_percentile_through_engine(self, tmp_path):
        rng = np.random.default_rng(4)
        n = 30_000
        ks = np.array(["a", "b"])[rng.integers(0, 2, n)]
        vs = rng.integers(0, 10_000, n).astype(np.int64)
        seg = build_segment(
            SCHEMA, {"k": ks, "v": vs, "f": np.zeros(n)},
            str(tmp_path / "gp"), TableConfig(table_name="t"), "gp0")
        engine = _engine_with(seg)
        rows = _rows(engine,
                     "SELECT k, PERCENTILE(v, 90) FROM t GROUP BY k ORDER BY k")
        for key, est in rows:
            grp = np.sort(vs[ks == key])
            rank = np.searchsorted(grp, est) / len(grp)
            assert abs(rank - 0.9) <= 0.02, (key, est, rank)

    def test_wire_roundtrip_of_digest_partials(self, tmp_path):
        from pinot_tpu.engine.datatable import decode, encode
        from pinot_tpu.query.optimizer import optimize_query
        from pinot_tpu.sql.compiler import compile_query

        rng = np.random.default_rng(8)
        n = 5000
        seg = build_segment(
            SCHEMA,
            {"k": np.array(["a", "b"])[rng.integers(0, 2, n)],
             "v": rng.integers(0, 1000, n).astype(np.int64),
             "f": np.zeros(n)},
            str(tmp_path / "wr"), TableConfig(table_name="t"), "wr0")
        engine = QueryEngine(device_executor=None)
        q = optimize_query(compile_query(
            "SELECT k, PERCENTILE(v, 50) FROM t GROUP BY k ORDER BY k"))
        merged = engine.execute_segments(q, [seg])
        again = decode(encode(merged))
        from pinot_tpu.engine.reduce import finalize

        assert finalize(q, again).rows == finalize(q, merged).rows