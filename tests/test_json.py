"""JSON column type, JSON index, JSON_MATCH, JSON_EXTRACT_SCALAR.

Reference analogs: ImmutableJsonIndexReader/JsonIndexCreator
(pinot-segment-local/.../readers/json/), JsonExtractScalar transform,
JsonMatchPredicate — including the same-flattened-doc semantics for
array wildcards.
"""

import json

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.jsonindex import flatten_doc

DOCS = [
    {"name": "ann", "age": 30,
     "addresses": [{"country": "us", "city": "nyc"},
                   {"country": "ca", "city": "yyz"}]},
    {"name": "bob", "age": 25,
     "addresses": [{"country": "us", "city": "sf"}], "vip": True},
    {"name": "cat", "age": 41, "addresses": [],
     "scores": [7, 9]},
    {"name": "dan"},  # no age, no addresses
    {"name": "eve", "age": 30,
     "addresses": [{"country": "de", "city": "ber"},
                   {"country": "us", "city": "aus"}]},
]


class TestFlatten:
    def test_nested_and_wildcard(self):
        rows = flatten_doc(DOCS[0])
        assert len(rows) == 2  # one per addresses element
        r0 = rows[0]
        assert r0["$.name"] == "ann"
        assert r0["$.addresses[0].country"] == "us"
        assert r0["$.addresses[*].country"] == "us"
        assert rows[1]["$.addresses[*].country"] == "ca"

    def test_scalar_array(self):
        rows = flatten_doc(DOCS[2])
        assert {r["$.scores[*]"] for r in rows} == {"7", "9"}

    def test_empty_doc_one_row(self):
        assert flatten_doc({}) == [{}]
        assert flatten_doc(None) == [{}]

    def test_bool_and_float_canonical(self):
        rows = flatten_doc({"a": True, "b": 3.0, "c": 2.5})
        assert rows[0] == {"$.a": "true", "$.b": "3", "$.c": "2.5"}


def _engine(tmp_path, with_index: bool):
    schema = Schema.build(
        name="people",
        dimensions=[("person", DataType.JSON), ("id", DataType.INT)],
    )
    idx = IndexingConfig(json_index_columns=["person"] if with_index else [])
    cfg = TableConfig(table_name="people", indexing=idx)
    col = np.asarray([json.dumps(d) for d in DOCS], dtype=np.str_)
    eng = QueryEngine(device_executor=None)
    tag = "idx" if with_index else "scan"
    seg = build_segment(schema, {"person": col, "id": np.arange(len(DOCS), dtype=np.int32)},
                        str(tmp_path / f"seg_{tag}"), cfg, f"s_{tag}")
    eng.add_segment("people", seg)
    return eng


@pytest.fixture(scope="module", params=[True, False], ids=["indexed", "scan"])
def engine(request, tmp_path_factory):
    return _engine(tmp_path_factory.mktemp("json"), request.param)


def ids(eng, match_expr):
    r = eng.execute(
        f"SELECT id FROM people WHERE JSON_MATCH(person, '{match_expr}') ORDER BY id")
    assert not r.get("exceptions"), r
    return [row[0] for row in r["resultTable"]["rows"]]


class TestJsonMatch:
    def test_eq_nested(self, engine):
        assert ids(engine, "\"$.name\" = \'\'ann\'\'") == [0]

    def test_wildcard_array(self, engine):
        assert ids(engine, "\"$.addresses[*].country\" = \'\'us\'\'") == [0, 1, 4]

    def test_exact_index_path(self, engine):
        assert ids(engine, "\"$.addresses[0].country\" = \'\'us\'\'") == [0, 1]

    def test_same_element_and_semantics(self, engine):
        # us+nyc in the SAME element: only ann. eve has us and aus but
        # us pairs with aus, not ber
        assert ids(engine,
                   "\"$.addresses[*].country\" = ''us'' AND "
                  "\"$.addresses[*].city\" = ''nyc''") == [0]
        assert ids(engine,
                   "\"$.addresses[*].country\" = ''de'' AND "
                  "\"$.addresses[*].city\" = ''aus''") == []

    def test_numeric_eq_and_in(self, engine):
        assert ids(engine, '"$.age" = 30') == [0, 4]
        assert ids(engine, '"$.age" IN (25, 41)') == [1, 2]

    def test_not_eq_requires_path(self, engine):
        # dan has no age: NE matches only docs where the path exists
        assert ids(engine, '"$.age" <> 30') == [1, 2]

    def test_is_null_and_not_null(self, engine):
        assert ids(engine, '"$.age" IS NULL') == [3]
        assert ids(engine, '"$.vip" IS NOT NULL') == [1]

    def test_range_numeric(self, engine):
        assert ids(engine, '"$.age" > 26 AND "$.age" <= 41') == [0, 2, 4]
        assert ids(engine, '"$.scores[*]" >= 8') == [2]

    def test_range_string_bounds(self, engine):
        # string bounds compare lexicographically, not crash (r3 review)
        assert ids(engine, "\"$.name\" > ''cat''") == [3, 4]
        assert ids(engine, "\"$.name\" >= ''ann'' AND \"$.name\" < ''c''") == [0, 1]

    def test_or_and_not(self, engine):
        assert ids(engine, "\"$.name\" = \'\'dan\'\' OR \"$.age\" = 25") == [1, 3]
        assert ids(engine, "NOT \"$.addresses[*].country\" = \'\'us\'\'") == [2, 3]

    def test_combined_with_regular_predicate(self, engine):
        r = engine.execute(
            "SELECT COUNT(*) FROM people WHERE id < 4 AND "
            "JSON_MATCH(person, '\"$.addresses[*].country\" = ''us''')")
        assert r["resultTable"]["rows"][0][0] == 2

    def test_explain_names_operator(self, engine):
        r = engine.execute(
            "EXPLAIN PLAN FOR SELECT COUNT(*) FROM people WHERE "
            "JSON_MATCH(person, '\"$.name\" = ''ann''')")
        ops = " ".join(row[0] for row in r["resultTable"]["rows"])
        assert "FILTER_JSON_INDEX" in ops or "FILTER_FULL_SCAN" in ops


class TestJsonExtractScalar:
    def test_extract_string_and_int(self, engine):
        r = engine.execute(
            "SELECT JSON_EXTRACT_SCALAR(person, '$.name', 'STRING'), "
            "JSON_EXTRACT_SCALAR(person, '$.age', 'INT', -1) "
            "FROM people ORDER BY id")
        rows = r["resultTable"]["rows"]
        assert rows == [["ann", 30], ["bob", 25], ["cat", 41],
                        ["dan", -1], ["eve", 30]]

    def test_extract_array_element(self, engine):
        r = engine.execute(
            "SELECT JSON_EXTRACT_SCALAR(person, '$.addresses[0].city', "
            "'STRING', 'none') FROM people ORDER BY id")
        assert [x[0] for x in r["resultTable"]["rows"]] == [
            "nyc", "sf", "none", "none", "ber"]

    def test_wildcard_path_rejected(self, engine):
        # [*] in a scalar path must error, not silently read $.addresses.city
        r = engine.execute(
            "SELECT JSON_EXTRACT_SCALAR(person, '$.addresses[*].city', "
            "'STRING', 'x') FROM people")
        assert r.get("exceptions")

    def test_group_by_extracted(self, engine):
        r = engine.execute(
            "SELECT JSON_EXTRACT_SCALAR(person, '$.age', 'INT', 0), COUNT(*) "
            "FROM people GROUP BY JSON_EXTRACT_SCALAR(person, '$.age', 'INT', 0) "
            "ORDER BY JSON_EXTRACT_SCALAR(person, '$.age', 'INT', 0)")
        assert r["resultTable"]["rows"] == [[0, 1], [25, 1], [30, 2], [41, 1]]


class TestJsonIndexConfigValidation:
    def test_requires_string_column(self, tmp_path):
        schema = Schema.build(name="t", dimensions=[("x", DataType.INT)])
        cfg = TableConfig(table_name="t",
                          indexing=IndexingConfig(json_index_columns=["x"]))
        with pytest.raises(ValueError, match="json index"):
            build_segment(schema, {"x": np.arange(3, dtype=np.int32)},
                          str(tmp_path / "s"), cfg, "s0")
