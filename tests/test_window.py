"""Multi-stage engine v2: window functions — differential suite.

ROW_NUMBER / RANK / DENSE_RANK / SUM / AVG / COUNT / MIN / MAX over
``OVER (PARTITION BY ... ORDER BY ...)`` agree across the device kernel
(ops/window.py: one sort + segmented scans), the host numpy mirror, and a
sqlite3 oracle (sqlite >= 3.25 implements standard window semantics,
including the RANGE UNBOUNDED PRECEDING .. CURRENT ROW default frame with
peer rows sharing frame values). Runs on sealed + consuming segments and
on solo + 8-virtual-device mesh engines.
"""

import math
import sqlite3

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.engine.device import DeviceExecutor
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.parallel.mesh import make_mesh
from pinot_tpu.storage.creator import build_segment

N = 3000


def _schema():
    return Schema.build(
        name="trades",
        dimensions=[("sym", DataType.STRING), ("venue", DataType.STRING),
                    ("ts", DataType.LONG)],
        metrics=[("px", DataType.DOUBLE), ("size", DataType.INT)],
    )


def _data(rng):
    return {
        "sym": np.array([f"sym_{i}" for i in range(12)])[
            rng.integers(0, 12, N)],
        "venue": np.array(["A", "B", "C"])[rng.integers(0, 3, N)],
        # unique per row: the deterministic ORDER BY tie-break
        "ts": np.arange(N, dtype=np.int64) * 10 + 5,
        "px": np.round(rng.uniform(5.0, 250.0, N), 2),
        "size": rng.integers(1, 500, N).astype(np.int32),
    }


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    rng = np.random.default_rng(23)
    data = _data(rng)
    base = tmp_path_factory.mktemp("winseg")
    engines = {}
    for name, dev in (("host", None), ("device", "auto"),
                      ("mesh", DeviceExecutor(mesh=make_mesh(8)))):
        eng = QueryEngine(device_executor=dev)
        half = N // 2
        for i, sl in enumerate([slice(0, half), slice(half, N)]):
            eng.add_segment("trades", build_segment(
                _schema(), {k: v[sl] for k, v in data.items()},
                str(base / f"t{name}{i}"), TableConfig(table_name="trades"),
                f"t{i}"))
        engines[name] = eng
    con = sqlite3.connect(":memory:")
    con.execute("CREATE TABLE trades (sym TEXT, venue TEXT, ts INT, "
                "px REAL, size INT)")
    con.executemany(
        "INSERT INTO trades VALUES (?,?,?,?,?)",
        list(zip(*(data[c].tolist() for c in
                   ("sym", "venue", "ts", "px", "size")))))
    return engines, con


def _norm(v):
    if v is None:
        return None
    if isinstance(v, bool):
        return float(v)
    if isinstance(v, (int, float)):
        f = float(v)
        return None if math.isnan(f) else round(f, 6)
    return v


def _rows(resp):
    assert not resp.get("exceptions"), resp.get("exceptions")
    return [[_norm(v) for v in r] for r in resp["resultTable"]["rows"]]


def check(setup, sql, oracle_sql=None,
          engines=("host", "device", "mesh")):
    eng_map, con = setup
    expected = [[_norm(v) for v in r]
                for r in con.execute(oracle_sql or sql).fetchall()]
    for name in engines:
        got = _rows(eng_map[name].execute(sql))
        assert got == expected, (
            f"{name} mismatch for {sql!r}:\n"
            f"got      {got[:5]}\nexpected {expected[:5]}")


class TestWindowParity:
    def test_row_number(self, setup):
        check(setup,
              "SELECT sym, ts, ROW_NUMBER() OVER (PARTITION BY sym "
              "ORDER BY ts) FROM trades WHERE size > 480 "
              "ORDER BY sym, ts LIMIT 40")

    def test_rank_dense_rank_with_ties(self, setup):
        # venue has heavy ties per sym: rank/dense_rank diverge
        check(setup,
              "SELECT sym, venue, RANK() OVER (PARTITION BY sym "
              "ORDER BY venue), DENSE_RANK() OVER (PARTITION BY sym "
              "ORDER BY venue) FROM trades WHERE size > 470 "
              "ORDER BY sym, venue, ts LIMIT 50")

    def test_running_sum(self, setup):
        check(setup,
              "SELECT sym, ts, SUM(size) OVER (PARTITION BY sym "
              "ORDER BY ts) FROM trades WHERE size > 450 "
              "ORDER BY sym, ts LIMIT 60")

    def test_running_sum_peers_share_frame(self, setup):
        # ORDER BY a tied key: peers must share the frame value (RANGE
        # default frame) — the classic running-sum-with-ties trap
        check(setup,
              "SELECT sym, venue, SUM(size) OVER (PARTITION BY sym "
              "ORDER BY venue) FROM trades WHERE size > 480 "
              "ORDER BY sym, venue, ts LIMIT 50")

    def test_avg_count_min_max(self, setup):
        check(setup,
              "SELECT sym, ts, AVG(px) OVER (PARTITION BY sym "
              "ORDER BY ts), COUNT(px) OVER (PARTITION BY sym "
              "ORDER BY ts), MIN(px) OVER (PARTITION BY sym "
              "ORDER BY ts), MAX(px) OVER (PARTITION BY sym ORDER BY ts) "
              "FROM trades WHERE size > 460 ORDER BY sym, ts LIMIT 60")

    def test_partition_total_no_order(self, setup):
        # no ORDER BY in the window: the frame is the whole partition
        check(setup,
              "SELECT sym, ts, SUM(size) OVER (PARTITION BY sym) "
              "FROM trades WHERE size > 470 ORDER BY sym, ts LIMIT 50")

    def test_no_partition_global_window(self, setup):
        check(setup,
              "SELECT ts, ROW_NUMBER() OVER (ORDER BY ts) "
              "FROM trades WHERE size > 490 ORDER BY ts LIMIT 40")

    def test_descending_order(self, setup):
        check(setup,
              "SELECT sym, ts, ROW_NUMBER() OVER (PARTITION BY sym "
              "ORDER BY ts DESC) FROM trades WHERE size > 480 "
              "ORDER BY sym, ts LIMIT 40")

    def test_multi_key_partition_and_order(self, setup):
        check(setup,
              "SELECT sym, venue, ts, ROW_NUMBER() OVER (PARTITION BY "
              "sym, venue ORDER BY px DESC, ts) FROM trades "
              "WHERE size > 475 ORDER BY sym, venue, ts LIMIT 50")

    def test_count_star_window(self, setup):
        check(setup,
              "SELECT sym, ts, COUNT(*) OVER (PARTITION BY sym "
              "ORDER BY ts) FROM trades WHERE size > 480 "
              "ORDER BY sym, ts LIMIT 40")

    def test_window_in_expression(self, setup):
        check(setup,
              "SELECT sym, ts, ROW_NUMBER() OVER (PARTITION BY sym "
              "ORDER BY ts) + 100 FROM trades WHERE size > 485 "
              "ORDER BY sym, ts LIMIT 30")

    def test_order_by_window_result(self, setup):
        check(setup,
              "SELECT sym, ts, SUM(size) OVER (PARTITION BY sym "
              "ORDER BY ts) FROM trades WHERE size > 480 "
              "ORDER BY SUM(size) OVER (PARTITION BY sym ORDER BY ts), "
              "sym, ts LIMIT 30")

    def test_window_over_join(self, setup, tmp_path_factory):
        # window over joined rows: rank trades within each category
        eng_map, con = setup
        base = tmp_path_factory.mktemp("windim")
        dim_schema = Schema.build(
            name="symbols",
            dimensions=[("symbol", DataType.STRING),
                        ("sector", DataType.STRING)],
            primary_key_columns=["symbol"])
        dim = {
            "symbol": np.array([f"sym_{i}" for i in range(12)]),
            "sector": np.array([f"sec_{i % 4}" for i in range(12)]),
        }
        for i, (name, eng) in enumerate(eng_map.items()):
            eng.add_segment("symbols", build_segment(
                dim_schema, dim, str(base / f"d{i}"),
                TableConfig(table_name="symbols", is_dim_table=True),
                "d0"))
        con.execute("CREATE TABLE IF NOT EXISTS symbols "
                    "(symbol TEXT, sector TEXT)")
        con.execute("DELETE FROM symbols")
        con.executemany("INSERT INTO symbols VALUES (?,?)",
                        list(zip(dim["symbol"].tolist(),
                                 dim["sector"].tolist())))
        check(setup,
              "SELECT s.sector, t.ts, ROW_NUMBER() OVER (PARTITION BY "
              "s.sector ORDER BY t.ts) FROM trades t "
              "JOIN symbols s ON t.sym = s.symbol WHERE t.size > 485 "
              "ORDER BY s.sector, t.ts LIMIT 40")


class TestWindowConsuming:
    def test_consuming_segment_parity(self, tmp_path):
        from pinot_tpu.storage.mutable import MutableSegment

        rng = np.random.default_rng(29)
        data = _data(rng)
        half = N // 2
        con = sqlite3.connect(":memory:")
        con.execute("CREATE TABLE trades (sym TEXT, venue TEXT, ts INT, "
                    "px REAL, size INT)")
        con.executemany(
            "INSERT INTO trades VALUES (?,?,?,?,?)",
            list(zip(*(data[c].tolist() for c in
                       ("sym", "venue", "ts", "px", "size")))))
        sql = ("SELECT sym, ts, ROW_NUMBER() OVER (PARTITION BY sym "
               "ORDER BY ts), SUM(size) OVER (PARTITION BY sym "
               "ORDER BY ts) FROM trades WHERE size > 460 "
               "ORDER BY sym, ts LIMIT 60")
        expected = [[_norm(v) for v in r]
                    for r in con.execute(sql).fetchall()]
        for name, dev in (("host", None), ("device", "auto")):
            eng = QueryEngine() if dev else QueryEngine(device_executor=None)
            eng.add_segment("trades", build_segment(
                _schema(), {k: v[:half] for k, v in data.items()},
                str(tmp_path / f"w{name}"), TableConfig(table_name="trades"),
                "t0"))
            ms = MutableSegment(_schema(), "trades__0__0__rt")
            ms.index_batch([{k: data[k][i].item() for k in data}
                            for i in range(half, N)])
            eng.add_segment("trades", ms)
            got = _rows(eng.execute(sql))
            assert got == expected, name


class TestWindowErrors:
    def test_window_with_group_by_rejected(self, setup):
        eng_map, _ = setup
        r = eng_map["host"].execute(
            "SELECT sym, SUM(size), ROW_NUMBER() OVER (ORDER BY sym) "
            "FROM trades GROUP BY sym")
        assert "GROUP BY" in r["exceptions"][0]["message"]

    def test_window_in_where_rejected(self, setup):
        eng_map, _ = setup
        r = eng_map["host"].execute(
            "SELECT sym FROM trades "
            "WHERE ROW_NUMBER() OVER (ORDER BY ts) < 5")
        assert r["exceptions"]

    def test_explicit_frame_rejected(self, setup):
        eng_map, _ = setup
        r = eng_map["host"].execute(
            "SELECT SUM(size) OVER (ORDER BY ts ROWS BETWEEN 1 "
            "PRECEDING AND CURRENT ROW) FROM trades")
        assert "frame" in r["exceptions"][0]["message"]

    def test_unknown_window_function(self, setup):
        eng_map, _ = setup
        r = eng_map["host"].execute(
            "SELECT NTILE(4) OVER (ORDER BY ts) FROM trades")
        assert "not a window function" in r["exceptions"][0]["message"]


class TestExplainWindow:
    def test_explain_window_lines(self, setup):
        eng_map, _ = setup
        r = eng_map["device"].execute(
            "EXPLAIN PLAN FOR SELECT sym, ROW_NUMBER() OVER "
            "(PARTITION BY sym ORDER BY ts DESC) FROM trades")
        lines = [row[0] for row in r["resultTable"]["rows"]]
        assert any("WINDOW(row_number() OVER (PARTITION BY trades.sym "
                   "ORDER BY trades.ts DESC))" in ln for ln in lines)
        assert any("STAGE_2_SELECT_WINDOW" in ln for ln in lines)
