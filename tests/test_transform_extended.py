"""DATETIMECONVERT / TIMECONVERT / array transforms / VALUEIN / MAPVALUE /
REGEXP_EXTRACT — oracle tests against python-computed expected values.

Reference analogs: DateTimeConversionTransformFunction.java:80,
TimeConversionTransformFunction.java, ArrayLengthTransformFunction.java:1,
ValueInTransformFunction.java:1, MapValueTransformFunction,
RegexpExtractTransformFunction.
"""

import datetime as dt

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment

N = 4_000


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(77)
    base = int(dt.datetime(2024, 1, 1).timestamp() * 1000)
    span = 90 * 86_400_000  # 90 days
    rows = {
        "name": np.array([f"user_{i % 37:02d}@host{i % 5}.example"
                          for i in range(N)]),
        "ts_ms": (base + rng.integers(0, span, N)).astype(np.int64),
        "ts_sec": None,  # filled below
        "tags": [list(np.array(["a", "b", "c", "d"])[
            rng.choice(4, size=rng.integers(0, 4), replace=False)])
            for _ in range(N)],
        "map_keys": [["k1", "k2", "k3"][: rng.integers(1, 4)] for _ in range(N)],
        "map_vals": None,  # filled below
        "v": rng.integers(1, 100, N).astype(np.int32),
    }
    rows["ts_sec"] = rows["ts_ms"] // 1000
    rows["map_vals"] = [
        list(rng.integers(0, 50, len(k))) for k in rows["map_keys"]
    ]
    return rows


@pytest.fixture(scope="module")
def eng(tmp_path_factory, data):
    schema = Schema.build(
        name="evt",
        dimensions=[("name", DataType.STRING)],
        multi_value_dimensions=[("tags", DataType.STRING),
                                ("map_keys", DataType.STRING),
                                ("map_vals", DataType.INT)],
        metrics=[("v", DataType.INT)],
        datetimes=[("ts_ms", DataType.LONG), ("ts_sec", DataType.LONG)],
    )
    d = str(tmp_path_factory.mktemp("tx") / "s0")
    build_segment(schema, data, d, TableConfig(table_name="evt"), "s0")
    e = QueryEngine()
    e.add_segment("evt", ImmutableSegment(d))
    return e


def rows_of(e, sql):
    r = e.execute(sql)
    assert not r.get("exceptions"), r
    return r["resultTable"]["rows"]


class TestTimeConvert:
    def test_millis_to_hours(self, eng, data):
        rows = rows_of(eng, "SELECT TIMECONVERT(ts_ms, 'MILLISECONDS', "
                            "'HOURS'), COUNT(*) FROM evt GROUP BY "
                            "TIMECONVERT(ts_ms, 'MILLISECONDS', 'HOURS') "
                            "ORDER BY COUNT(*) DESC, "
                            "TIMECONVERT(ts_ms, 'MILLISECONDS', 'HOURS') LIMIT 5")
        import collections

        want = collections.Counter(
            (data["ts_ms"] // 3_600_000).tolist())
        expect = sorted(want.items(), key=lambda kv: (-kv[1], kv[0]))[:5]
        assert [(r[0], r[1]) for r in rows] == expect

    def test_seconds_to_days_truncates(self, eng, data):
        rows = rows_of(eng, "SELECT MAX(TIMECONVERT(ts_sec, 'SECONDS', "
                            "'DAYS')) FROM evt")
        assert rows[0][0] == float((data["ts_sec"].max() * 1000) // 86_400_000)

    def test_roundtrip_identity(self, eng, data):
        rows = rows_of(eng, "SELECT SUM(TIMECONVERT(ts_ms, 'MILLISECONDS', "
                            "'MILLISECONDS')) FROM evt")
        assert rows[0][0] == float(data["ts_ms"].sum())


class TestDateTimeConvert:
    def test_epoch_to_epoch_bucketing(self, eng, data):
        # 1:MILLISECONDS:EPOCH → 1:HOURS:EPOCH at 1-day granularity:
        # bucket to days, expressed in hours (reference example shape)
        sql = ("SELECT DATETIMECONVERT(ts_ms, '1:MILLISECONDS:EPOCH', "
               "'1:HOURS:EPOCH', '1:DAYS'), COUNT(*) FROM evt "
               "GROUP BY DATETIMECONVERT(ts_ms, '1:MILLISECONDS:EPOCH', "
               "'1:HOURS:EPOCH', '1:DAYS') ORDER BY "
               "DATETIMECONVERT(ts_ms, '1:MILLISECONDS:EPOCH', "
               "'1:HOURS:EPOCH', '1:DAYS') LIMIT 3")
        rows = rows_of(eng, sql)
        import collections

        days = (data["ts_ms"] // 86_400_000) * 24
        want = collections.Counter(days.tolist())
        expect = sorted(want.items())[:3]
        assert [(r[0], r[1]) for r in rows] == expect

    def test_epoch_sized_units(self, eng, data):
        # 5-minute input epochs: value = ms // 300000
        sql = ("SELECT MIN(DATETIMECONVERT(ts_ms, '1:MILLISECONDS:EPOCH', "
               "'5:MINUTES:EPOCH', '5:MINUTES')) FROM evt")
        rows = rows_of(eng, sql)
        assert rows[0][0] == float(data["ts_ms"].min() // 300_000)

    def test_sdf_output(self, eng, data):
        sql = ("SELECT DATETIMECONVERT(ts_ms, '1:MILLISECONDS:EPOCH', "
               "'1:DAYS:SIMPLE_DATE_FORMAT:yyyy-MM-dd', '1:DAYS'), COUNT(*) "
               "FROM evt GROUP BY DATETIMECONVERT(ts_ms, "
               "'1:MILLISECONDS:EPOCH', '1:DAYS:SIMPLE_DATE_FORMAT:yyyy-MM-dd'"
               ", '1:DAYS') ORDER BY DATETIMECONVERT(ts_ms, "
               "'1:MILLISECONDS:EPOCH', '1:DAYS:SIMPLE_DATE_FORMAT:yyyy-MM-dd'"
               ", '1:DAYS') LIMIT 2")
        rows = rows_of(eng, sql)
        day0 = int(data["ts_ms"].min() // 86_400_000)
        want0 = (dt.datetime(1970, 1, 1)
                 + dt.timedelta(days=day0)).strftime("%Y-%m-%d")
        assert rows[0][0] == want0

    def test_sdf_input(self, eng, data):
        # SDF input parses back to the same day buckets as epoch input
        sql_epoch = ("SELECT COUNT(*) FROM evt WHERE DATETIMECONVERT(ts_ms, "
                     "'1:MILLISECONDS:EPOCH', '1:DAYS:EPOCH', '1:DAYS') = {}")
        day0 = int(data["ts_ms"].min() // 86_400_000)
        a = rows_of(eng, sql_epoch.format(day0))
        want = int(np.sum(data["ts_ms"] // 86_400_000 == day0))
        assert a[0][0] == want


class TestArrayTransforms:
    def test_arraylength(self, eng, data):
        rows = rows_of(eng, "SELECT SUM(ARRAYLENGTH(tags)) FROM evt")
        assert rows[0][0] == float(sum(len(t) for t in data["tags"]))

    def test_cardinality_alias(self, eng, data):
        rows = rows_of(eng, "SELECT MAX(CARDINALITY(tags)) FROM evt")
        assert rows[0][0] == float(max(len(t) for t in data["tags"]))

    def test_arraysum_avg_min_max(self, eng, data):
        rows = rows_of(
            eng, "SELECT SUM(ARRAYSUM(map_vals)), MIN(ARRAYMIN(map_vals)), "
                 "MAX(ARRAYMAX(map_vals)) FROM evt")
        assert rows[0][0] == float(sum(sum(v) for v in data["map_vals"]))
        assert rows[0][1] == float(min(min(v) for v in data["map_vals"]))
        assert rows[0][2] == float(max(max(v) for v in data["map_vals"]))

    def test_valuein_with_arraylength(self, eng, data):
        rows = rows_of(
            eng, "SELECT SUM(ARRAYLENGTH(VALUEIN(tags, 'a', 'c'))) FROM evt")
        want = sum(len({"a", "c"} & set(t)) for t in data["tags"])
        assert rows[0][0] == float(want)

    def test_valuein_selection(self, eng, data):
        rows = rows_of(eng, "SELECT VALUEIN(tags, 'b') FROM evt LIMIT 5")
        for r, t in zip(rows, data["tags"][:5]):
            assert r[0] == (["b"] if "b" in t else [])


class TestMapValue:
    def test_mapvalue_hit_and_miss(self, eng, data):
        rows = rows_of(
            eng, "SELECT SUM(MAPVALUE(map_keys, 'k2', map_vals)) FROM evt")
        want = 0
        for ks, vs in zip(data["map_keys"], data["map_vals"]):
            if "k2" in ks:
                want += vs[ks.index("k2")]
        assert rows[0][0] == float(want)


class TestRegexpExtract:
    def test_group_extract(self, eng, data):
        rows = rows_of(
            eng, "SELECT REGEXP_EXTRACT(name, 'user_(\\d+)@', 1), COUNT(*) "
                 "FROM evt GROUP BY REGEXP_EXTRACT(name, 'user_(\\d+)@', 1) "
                 "ORDER BY REGEXP_EXTRACT(name, 'user_(\\d+)@', 1) LIMIT 3")
        import collections

        want = collections.Counter(n.split("_")[1].split("@")[0]
                                   for n in data["name"])
        expect = sorted(want.items())[:3]
        assert [(r[0], r[1]) for r in rows] == expect

    def test_no_match_default(self, eng):
        rows = rows_of(
            eng, "SELECT REGEXP_EXTRACT(name, 'zzz(\\d+)', 1, 'none'), "
                 "COUNT(*) FROM evt GROUP BY "
                 "REGEXP_EXTRACT(name, 'zzz(\\d+)', 1, 'none')")
        assert rows[0][0] == "none"
        assert rows[0][1] == N
