"""Realtime ingestion tests: mutable segments, stream consume loop, commit,
restart-resume, flaky consumers, upsert.

Reference analogs: MutableSegmentImpl tests, LLCRealtimeClusterIntegrationTest
(rows queryable while consuming, segment commit), FlakyConsumerRealtime-
ClusterIntegrationTest (consumer that randomly throws must not lose data),
upsert integration tests.
"""

import time

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import StreamConfig, TableConfig, TableType, UpsertConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.realtime.manager import RealtimeTableDataManager
from pinot_tpu.storage.mutable import MutableSegment
from pinot_tpu.stream.memory_stream import TopicRegistry
from pinot_tpu.stream.spi import create_consumer_factory


def make_schema(pk=False):
    return Schema.build(
        name="events",
        dimensions=[("user", DataType.STRING), ("action", DataType.STRING)],
        metrics=[("amount", DataType.INT)],
        datetimes=[("ts", DataType.LONG)],
        primary_key_columns=["user"] if pk else [],
    )


def wait_until(cond, timeout=10.0, interval=0.02):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


class TestMutableSegment:
    def test_index_and_query(self):
        seg = MutableSegment(make_schema(), "m0")
        for i in range(100):
            seg.index({"user": f"u{i % 5}", "action": "click", "amount": i, "ts": i})
        assert seg.n_docs == 100
        eng = QueryEngine()
        eng.table("events").add_segment(seg)
        r = eng.execute("SELECT user, SUM(amount) FROM events GROUP BY user ORDER BY user")
        assert len(r["resultTable"]["rows"]) == 5
        assert r["resultTable"]["rows"][0][0] == "u0"
        # selection + filter on the consuming segment
        r = eng.execute("SELECT COUNT(*) FROM events WHERE action = 'click' AND amount >= 50")
        assert r["resultTable"]["rows"][0][0] == 50

    def test_seal_equivalence(self, tmp_path):
        seg = MutableSegment(make_schema(), "m1")
        rng = np.random.default_rng(5)
        for i in range(500):
            seg.index({"user": f"u{rng.integers(0, 20)}", "action": "a", "amount": int(rng.integers(0, 100)), "ts": i})
        eng = QueryEngine()
        eng.table("events").add_segment(seg)
        before = eng.execute("SELECT user, SUM(amount), COUNT(*) FROM events GROUP BY user ORDER BY user LIMIT 100")
        sealed = seg.seal(str(tmp_path / "sealed"))
        eng2 = QueryEngine()
        eng2.table("events").add_segment(sealed)
        after = eng2.execute("SELECT user, SUM(amount), COUNT(*) FROM events GROUP BY user ORDER BY user LIMIT 100")
        assert before["resultTable"]["rows"] == after["resultTable"]["rows"]
        assert sealed.column_metadata("user").is_sorted in (True, False)  # real metadata present

    def test_missing_column_gets_null_default(self):
        seg = MutableSegment(make_schema(), "m2")
        seg.index({"user": "u1", "ts": 1})  # no action/amount
        assert seg.n_docs == 1
        assert seg.values("amount")[0] == make_schema().field("amount").null_value()


def _realtime_setup(tmp_path, topic_name, n_partitions=2, flush_rows=200, upsert=False,
                    cmp_col="ts"):
    TopicRegistry.delete(topic_name)
    topic = TopicRegistry.create(topic_name, n_partitions)
    cfg = TableConfig(
        table_name="events",
        table_type=TableType.REALTIME,
        upsert=UpsertConfig(mode="FULL", comparison_column=cmp_col) if upsert else UpsertConfig(),
        stream=StreamConfig(
            stream_type="memory",
            topic=topic_name,
            decoder="json",
            segment_flush_threshold_rows=flush_rows,
            segment_flush_threshold_seconds=3600,
        ),
    )
    eng = QueryEngine()
    mgr = RealtimeTableDataManager(
        make_schema(pk=upsert), cfg, eng.table("events"), str(tmp_path / "rt")
    )
    return topic, cfg, eng, mgr


class TestRealtimeConsumption:
    def test_consume_query_commit(self, tmp_path):
        topic, cfg, eng, mgr = _realtime_setup(tmp_path, "t_consume", flush_rows=150)
        mgr.start()
        try:
            for i in range(500):
                topic.publish_json(
                    {"user": f"u{i % 10}", "action": "view", "amount": i % 50, "ts": i},
                    partition=i % 2,
                )
            assert wait_until(lambda: _count(eng) == 500), _count(eng)
            # commits happened (150-row flush threshold, 250 rows/partition)
            assert wait_until(
                lambda: sum(m.commits for m in mgr.partition_managers.values()) >= 2
            )
            # data correct across sealed + consuming segments
            r = eng.execute("SELECT user, COUNT(*) FROM events GROUP BY user ORDER BY user LIMIT 20")
            assert [row[1] for row in r["resultTable"]["rows"]] == [50] * 10
        finally:
            mgr.stop()

    def test_restart_resumes_from_checkpoint(self, tmp_path):
        topic, cfg, eng, mgr = _realtime_setup(tmp_path, "t_resume", n_partitions=1, flush_rows=100)
        mgr.start()
        for i in range(250):
            topic.publish_json({"user": "u1", "action": "a", "amount": 1, "ts": i})
        assert wait_until(lambda: _count(eng) == 250)
        mgr.stop(commit_remaining=True)  # commits the 50-row tail too

        # "restart": new engine+manager over the same data dir and topic
        eng2 = QueryEngine()
        mgr2 = RealtimeTableDataManager(
            make_schema(), cfg, eng2.table("events"), str(tmp_path / "rt")
        )
        # earlier committed segments are reloaded from the registry by the
        # server layer in a real deployment, but the manager itself reconciles
        # the LAST checkpointed segment (crash-window repair between
        # record_commit and publication); the consume loop then resumes at
        # the checkpointed offset (no re-consumption of committed rows)
        mgr2.start()
        try:
            reconciled = _count(eng2)  # docs of the last committed segment
            assert 0 < reconciled <= 250, reconciled
            for i in range(50):
                topic.publish_json({"user": "u2", "action": "b", "amount": 1, "ts": 250 + i})
            assert wait_until(lambda: _count(eng2) == reconciled + 50), _count(eng2)
            r = eng2.execute("SELECT COUNT(*) FROM events WHERE user = 'u2'")
            assert r["resultTable"]["rows"][0][0] == 50  # u1 rows never duplicated
        finally:
            mgr2.stop(commit_remaining=False)

    def test_flaky_consumer_loses_nothing(self, tmp_path, monkeypatch):
        topic, cfg, eng, mgr = _realtime_setup(tmp_path, "t_flaky", n_partitions=1, flush_rows=10_000)
        # wrap the factory to produce consumers that fail every 3rd fetch
        real_factory = mgr._factory
        calls = {"n": 0}

        class FlakyConsumer:
            def __init__(self, inner):
                self.inner = inner

            def fetch_messages(self, offset, timeout_ms):
                calls["n"] += 1
                if calls["n"] % 3 == 0:
                    raise RuntimeError("flaky!")
                return self.inner.fetch_messages(offset, timeout_ms)

            def close(self):
                self.inner.close()

        class FlakyFactory:
            def partition_count(self):
                return real_factory.partition_count()

            def earliest_offset(self, p):
                return real_factory.earliest_offset(p)

            def create_partition_consumer(self, p):
                return FlakyConsumer(real_factory.create_partition_consumer(p))

        mgr._factory = FlakyFactory()
        mgr.start()
        try:
            for i in range(300):
                topic.publish_json({"user": f"u{i}", "action": "x", "amount": 1, "ts": i})
            assert wait_until(lambda: _count(eng) == 300, timeout=15), _count(eng)
            assert calls["n"] >= 3  # flakiness actually exercised
        finally:
            mgr.stop(commit_remaining=False)


class TestUpsert:
    def test_latest_record_wins(self, tmp_path):
        topic, cfg, eng, mgr = _realtime_setup(tmp_path, "t_upsert", n_partitions=1,
                                               flush_rows=10_000, upsert=True)
        mgr.start()
        try:
            topic.publish_json({"user": "alice", "action": "a", "amount": 10, "ts": 100})
            topic.publish_json({"user": "bob", "action": "b", "amount": 20, "ts": 100})
            topic.publish_json({"user": "alice", "action": "c", "amount": 99, "ts": 200})
            assert wait_until(lambda: _total_indexed(mgr) == 3)
            r = eng.execute("SELECT COUNT(*) FROM events")
            assert r["resultTable"]["rows"][0][0] == 2  # one alive row per key
            r = eng.execute("SELECT SUM(amount) FROM events WHERE user = 'alice'")
            assert r["resultTable"]["rows"][0][0] == 99  # latest ts wins
        finally:
            mgr.stop(commit_remaining=False)

    def test_out_of_order_ignored(self, tmp_path):
        topic, cfg, eng, mgr = _realtime_setup(tmp_path, "t_upsert2", n_partitions=1,
                                               flush_rows=10_000, upsert=True)
        mgr.start()
        try:
            topic.publish_json({"user": "x", "action": "new", "amount": 5, "ts": 500})
            topic.publish_json({"user": "x", "action": "old", "amount": 7, "ts": 100})
            assert wait_until(lambda: _total_indexed(mgr) == 2)
            r = eng.execute("SELECT SUM(amount) FROM events WHERE user = 'x'")
            assert r["resultTable"]["rows"][0][0] == 5  # older comparison loses
        finally:
            mgr.stop(commit_remaining=False)

    def test_upsert_restart_reconcile_dedupes(self, tmp_path):
        """Crash-window reconcile on an upsert table: the republished sealed
        segment must replay its keys through the fresh upsert manager so
        stale duplicates stay invalid and remain overridable."""
        topic, cfg, eng, mgr = _realtime_setup(tmp_path, "t_upsert_rc", n_partitions=1,
                                               flush_rows=10_000, upsert=True)
        mgr.start()
        topic.publish_json({"user": "a", "action": "1", "amount": 1, "ts": 1})
        topic.publish_json({"user": "a", "action": "2", "amount": 50, "ts": 2})
        topic.publish_json({"user": "b", "action": "1", "amount": 7, "ts": 1})
        assert wait_until(lambda: _total_indexed(mgr) == 3)
        mgr.stop(commit_remaining=True)  # seals the 3-row segment + checkpoint

        # "restart": fresh engine + manager over the same dir; reconcile
        # republishes the sealed segment (no persisted validDocIds)
        eng2 = QueryEngine()
        mgr2 = RealtimeTableDataManager(
            make_schema(pk=True), cfg, eng2.table("events"), str(tmp_path / "rt")
        )
        mgr2.start()
        try:
            assert _count(eng2) == 2  # a deduped (ts=2 wins), b
            assert _total(eng2, "SELECT SUM(amount) FROM events WHERE user = 'a'") == 50
            # the reconciled rows must still be overridable by new stream rows
            topic.publish_json({"user": "a", "action": "3", "amount": 900, "ts": 3})
            assert wait_until(
                lambda: _total(eng2, "SELECT SUM(amount) FROM events WHERE user = 'a'") == 900
            )
            assert _count(eng2) == 2
        finally:
            mgr2.stop(commit_remaining=False)

    def test_upsert_restart_replays_all_sealed_segments(self, tmp_path):
        """A key overridden across segment boundaries must stay deduped
        after restart: EVERY sealed segment's keys replay in commit order,
        not just the checkpointed one (r2 review finding)."""
        topic, cfg, eng, mgr = _realtime_setup(tmp_path, "t_upsert_multi", n_partitions=1,
                                               flush_rows=2, upsert=True)
        mgr.start()
        topic.publish_json({"user": "a", "action": "1", "amount": 1, "ts": 1})
        topic.publish_json({"user": "b", "action": "1", "amount": 2, "ts": 1})  # seals S0
        assert wait_until(lambda: sum(m.commits for m in mgr.partition_managers.values()) >= 1)
        topic.publish_json({"user": "a", "action": "2", "amount": 70, "ts": 2})
        topic.publish_json({"user": "c", "action": "1", "amount": 5, "ts": 1})  # seals S1
        assert wait_until(lambda: sum(m.commits for m in mgr.partition_managers.values()) >= 2)
        mgr.stop(commit_remaining=False)

        eng2 = QueryEngine()
        mgr2 = RealtimeTableDataManager(
            make_schema(pk=True), cfg, eng2.table("events"), str(tmp_path / "rt")
        )
        mgr2.start()
        try:
            assert _count(eng2) == 3  # a (ts=2 wins), b, c
            assert _total(eng2, "SELECT SUM(amount) FROM events WHERE user = 'a'") == 70
        finally:
            mgr2.stop(commit_remaining=False)

    def test_upsert_restart_no_comparison_column(self, tmp_path):
        """Upsert with no comparison column (arrival order wins) must keep
        arrival order ACROSS sealed segments on restart: replay uses a
        running doc base, not per-segment indexes (r2 advisor finding —
        per-segment range(n_docs) made a later segment's low doc index lose
        to an earlier segment's high one, flipping SUM from 70 to 1)."""
        topic, cfg, eng, mgr = _realtime_setup(tmp_path, "t_upsert_nocmp", n_partitions=1,
                                               flush_rows=2, upsert=True, cmp_col=None)
        mgr.start()
        topic.publish_json({"user": "a", "action": "1", "amount": 1, "ts": 1})
        topic.publish_json({"user": "b", "action": "1", "amount": 2, "ts": 1})  # seals S0
        assert wait_until(lambda: sum(m.commits for m in mgr.partition_managers.values()) >= 1)
        topic.publish_json({"user": "a", "action": "2", "amount": 70, "ts": 2})
        topic.publish_json({"user": "c", "action": "1", "amount": 5, "ts": 1})  # seals S1
        assert wait_until(lambda: sum(m.commits for m in mgr.partition_managers.values()) >= 2)
        mgr.stop(commit_remaining=False)

        eng2 = QueryEngine()
        mgr2 = RealtimeTableDataManager(
            make_schema(pk=True), cfg, eng2.table("events"), str(tmp_path / "rt")
        )
        mgr2.start()
        try:
            assert _count(eng2) == 3  # a (later arrival wins), b, c
            assert _total(eng2, "SELECT SUM(amount) FROM events WHERE user = 'a'") == 70
            # new stream rows still override the replayed state
            topic.publish_json({"user": "a", "action": "3", "amount": 500, "ts": 0})
            assert wait_until(
                lambda: _total(eng2, "SELECT SUM(amount) FROM events WHERE user = 'a'") == 500
            )
        finally:
            mgr2.stop(commit_remaining=False)

    def test_upsert_survives_commit(self, tmp_path):
        topic, cfg, eng, mgr = _realtime_setup(tmp_path, "t_upsert3", n_partitions=1,
                                               flush_rows=3, upsert=True)
        mgr.start()
        try:
            topic.publish_json({"user": "a", "action": "1", "amount": 1, "ts": 1})
            topic.publish_json({"user": "b", "action": "1", "amount": 2, "ts": 1})
            topic.publish_json({"user": "c", "action": "1", "amount": 3, "ts": 1})  # flush
            assert wait_until(
                lambda: sum(m.commits for m in mgr.partition_managers.values()) >= 1
            )
            # override a key that now lives in the SEALED segment
            topic.publish_json({"user": "a", "action": "2", "amount": 100, "ts": 2})
            assert wait_until(lambda: _total(eng, "SELECT SUM(amount) FROM events") == 105)
            r = eng.execute("SELECT COUNT(*) FROM events")
            assert r["resultTable"]["rows"][0][0] == 3
        finally:
            mgr.stop(commit_remaining=False)


def _partial_setup(tmp_path, topic_name, strategies, flush_rows=10_000,
                   cmp_col="ts"):
    TopicRegistry.delete(topic_name)
    topic = TopicRegistry.create(topic_name, 1)
    cfg = TableConfig(
        table_name="events",
        table_type=TableType.REALTIME,
        upsert=UpsertConfig(mode="PARTIAL", comparison_column=cmp_col,
                            partial_upsert_strategies=strategies),
        stream=StreamConfig(
            stream_type="memory", topic=topic_name, decoder="json",
            segment_flush_threshold_rows=flush_rows,
            segment_flush_threshold_seconds=3600,
        ),
    )
    eng = QueryEngine()
    mgr = RealtimeTableDataManager(
        make_schema(pk=True), cfg, eng.table("events"), str(tmp_path / "rt")
    )
    return topic, cfg, eng, mgr


class TestPartialUpsert:
    """upsert/merger/ analog: per-column merge of the previous version."""

    def test_increment_and_ignore(self, tmp_path):
        topic, cfg, eng, mgr = _partial_setup(
            tmp_path, "t_partial1",
            {"amount": "INCREMENT", "action": "IGNORE"})
        mgr.start()
        try:
            topic.publish_json({"user": "a", "action": "first", "amount": 10, "ts": 1})
            topic.publish_json({"user": "a", "action": "second", "amount": 5, "ts": 2})
            assert wait_until(lambda: _total_indexed(mgr) == 2)
            r = eng.execute("SELECT action, amount FROM events WHERE user = 'a'")
            assert r["resultTable"]["rows"] == [["first", 15]]
        finally:
            mgr.stop(commit_remaining=False)

    def test_missing_column_carries_over(self, tmp_path):
        topic, cfg, eng, mgr = _partial_setup(tmp_path, "t_partial2", {})
        mgr.start()
        try:
            topic.publish_json({"user": "a", "action": "x", "amount": 42, "ts": 1})
            topic.publish_json({"user": "a", "ts": 2})  # no action/amount
            assert wait_until(lambda: _total_indexed(mgr) == 2)
            r = eng.execute("SELECT action, amount FROM events WHERE user = 'a'")
            assert r["resultTable"]["rows"] == [["x", 42]]
        finally:
            mgr.stop(commit_remaining=False)

    def test_out_of_order_does_not_merge(self, tmp_path):
        topic, cfg, eng, mgr = _partial_setup(
            tmp_path, "t_partial3", {"amount": "INCREMENT"})
        mgr.start()
        try:
            topic.publish_json({"user": "a", "action": "n", "amount": 10, "ts": 500})
            topic.publish_json({"user": "a", "action": "o", "amount": 7, "ts": 100})
            assert wait_until(lambda: _total_indexed(mgr) == 2)
            assert _total(eng, "SELECT SUM(amount) FROM events WHERE user = 'a'") == 10
        finally:
            mgr.stop(commit_remaining=False)

    def test_merge_from_sealed_segment_and_restart(self, tmp_path):
        topic, cfg, eng, mgr = _partial_setup(
            tmp_path, "t_partial4", {"amount": "INCREMENT", "action": "IGNORE"},
            flush_rows=2)
        mgr.start()
        topic.publish_json({"user": "a", "action": "keep", "amount": 1, "ts": 1})
        topic.publish_json({"user": "b", "action": "y", "amount": 2, "ts": 1})  # seals S0
        assert wait_until(lambda: sum(m.commits for m in mgr.partition_managers.values()) >= 1)
        # previous version now lives in a sealed segment
        topic.publish_json({"user": "a", "action": "drop", "amount": 9, "ts": 2})
        assert wait_until(
            lambda: _total(eng, "SELECT SUM(amount) FROM events WHERE user = 'a'") == 10)
        r = eng.execute("SELECT action FROM events WHERE user = 'a'")
        assert r["resultTable"]["rows"] == [["keep"]]
        mgr.stop(commit_remaining=True)

        # restart: sealed rows hold merged values, replay reconstructs state
        eng2 = QueryEngine()
        mgr2 = RealtimeTableDataManager(
            make_schema(pk=True), cfg, eng2.table("events"), str(tmp_path / "rt")
        )
        mgr2.start()
        try:
            assert _total(eng2, "SELECT SUM(amount) FROM events WHERE user = 'a'") == 10
            topic.publish_json({"user": "a", "action": "later", "amount": 5, "ts": 3})
            assert wait_until(
                lambda: _total(eng2, "SELECT SUM(amount) FROM events WHERE user = 'a'") == 15)
            r = eng2.execute("SELECT action FROM events WHERE user = 'a'")
            assert r["resultTable"]["rows"] == [["keep"]]
        finally:
            mgr2.stop(commit_remaining=False)

    def test_explicit_null_carries_previous(self, tmp_path):
        """An explicit JSON null in the incoming event must keep the
        previous value, not crash the merge (r3 review finding: the
        TypeError made the whole event a dropped poison message)."""
        topic, cfg, eng, mgr = _partial_setup(
            tmp_path, "t_partial_null", {"amount": "INCREMENT"})
        mgr.start()
        try:
            topic.publish_json({"user": "a", "action": "x", "amount": 10, "ts": 1})
            topic.publish_json({"user": "a", "action": "y", "amount": None, "ts": 2})
            assert wait_until(lambda: _total_indexed(mgr) == 2)
            assert not any(
                m.index_errors for m in mgr.partition_managers.values())
            r = eng.execute("SELECT action, amount FROM events WHERE user = 'a'")
            assert r["resultTable"]["rows"] == [["y", 10]]
        finally:
            mgr.stop(commit_remaining=False)

    def test_previous_null_takes_incoming(self, tmp_path):
        """IGNORE must not resurrect a default-fill value over a real
        incoming one when the previous version was null (r3 review
        finding: read_row couldn't distinguish null from default)."""
        topic, cfg, eng, mgr = _partial_setup(
            tmp_path, "t_partial_null2", {"action": "IGNORE"})
        mgr.start()
        try:
            topic.publish_json({"user": "a", "amount": 1, "ts": 1})  # action null
            topic.publish_json({"user": "a", "action": "real", "amount": 2, "ts": 2})
            assert wait_until(lambda: _total_indexed(mgr) == 2)
            r = eng.execute("SELECT action FROM events WHERE user = 'a'")
            assert r["resultTable"]["rows"] == [["real"]]
            # a still-null carried-over column stays null for IS_NULL
            topic.publish_json({"user": "b", "amount": 1, "ts": 1})
            topic.publish_json({"user": "b", "amount": 2, "ts": 2})
            assert wait_until(lambda: _total_indexed(mgr) == 4)
            r = eng.execute(
                "SELECT COUNT(*) FROM events WHERE user = 'b' AND action IS NULL")
            assert r["resultTable"]["rows"][0][0] == 1
        finally:
            mgr.stop(commit_remaining=False)

    def test_strategy_validation(self):
        from pinot_tpu.realtime.merger import PartialUpsertMerger

        with pytest.raises(ValueError, match="unknown"):
            PartialUpsertMerger(
                make_schema(pk=True),
                UpsertConfig(mode="PARTIAL",
                             partial_upsert_strategies={"amount": "BOGUS"}))
        with pytest.raises(ValueError, match="key/comparison"):
            PartialUpsertMerger(
                make_schema(pk=True),
                UpsertConfig(mode="PARTIAL", comparison_column="ts",
                             partial_upsert_strategies={"ts": "MAX"}))

    def test_strategy_functions(self):
        from pinot_tpu.realtime.merger import STRATEGIES

        assert STRATEGIES["APPEND"]([1, 2], [3]) == [1, 2, 3]
        assert STRATEGIES["APPEND"](1, 2) == [1, 2]
        assert STRATEGIES["UNION"]([1, 2], [2, 3]) == [1, 2, 3]
        assert STRATEGIES["MAX"](3, 5) == 5
        assert STRATEGIES["MIN"](3, 5) == 3
        assert STRATEGIES["OVERWRITE"]("a", "b") == "b"
        assert STRATEGIES["IGNORE"]("a", "b") == "a"
        assert STRATEGIES["INCREMENT"](2, 3) == 5


def _count(eng):
    r = eng.execute("SELECT COUNT(*) FROM events")
    if r.get("exceptions"):
        return -1
    return r["resultTable"]["rows"][0][0]


def _total(eng, sql):
    r = eng.execute(sql)
    if r.get("exceptions"):
        return None
    return r["resultTable"]["rows"][0][0]


def _total_indexed(mgr):
    """Docs in the current consuming segments (tests using this don't flush)."""
    return sum(m.segment.n_docs for m in mgr.partition_managers.values())


class TestOrphanSegments:
    def test_same_sequence_orphan_quarantined(self, tmp_path):
        """A crash between seal() and record_commit() leaves a sealed dir
        that shares its sequence with the later re-consumed committed
        segment (names embed creation time, so they differ). Restart must
        publish only the checkpoint-named segment and quarantine the orphan
        — publishing both doubles every count (r2 advisor finding)."""
        import shutil

        topic, cfg, eng, mgr = _realtime_setup(tmp_path, "t_orphan", n_partitions=1,
                                               flush_rows=100)
        mgr.start()
        for i in range(150):
            topic.publish_json({"user": f"u{i % 5}", "action": "a", "amount": 1, "ts": i})
        assert wait_until(lambda: _count(eng) == 150)
        mgr.stop(commit_remaining=True)

        # forge the orphan: same table/partition/sequence as the last commit,
        # different creation timestamp
        rt = tmp_path / "rt"
        import json as _json

        ckpt = _json.load(open(rt / "checkpoints.json"))
        committed = ckpt["events/0"]["segment"]
        seq = committed.split("__")[2]
        orphan = f"events__0__{seq}__19990101T000000Z"
        shutil.copytree(rt / committed, rt / orphan)

        eng2 = QueryEngine()
        mgr2 = RealtimeTableDataManager(
            make_schema(), cfg, eng2.table("events"), str(tmp_path / "rt")
        )
        mgr2.start()
        try:
            # only the committed segment is published — no doubled rows
            assert 0 < _count(eng2) <= 150
            assert not (rt / orphan).exists()
            assert (rt / "_orphans" / orphan).exists()
        finally:
            mgr2.stop(commit_remaining=False)


    def test_older_sequence_orphan_quarantined(self, tmp_path):
        """An orphan whose sequence has been PASSED by later commits must
        still be quarantined on a later restart — the checkpoint's seq→name
        log identifies it (code-review finding: without the log, an old
        orphan was replayed, inflating cmp_base past the resume offset so
        live upsert updates lost to stale replayed rows)."""
        import shutil

        topic, cfg, eng, mgr = _realtime_setup(tmp_path, "t_orphan2", n_partitions=1,
                                               flush_rows=50)
        mgr.start()
        for wave in range(3):  # one ≥50-row commit per wave (flush is per fetch)
            for i in range(60):
                topic.publish_json({"user": f"u{i % 5}", "action": "a",
                                    "amount": 1, "ts": wave * 60 + i})
            assert wait_until(
                lambda: sum(m.commits for m in mgr.partition_managers.values()) >= wave + 1
            )
        mgr.stop(commit_remaining=True)

        rt = tmp_path / "rt"
        import json as _json

        ckpt = _json.load(open(rt / "checkpoints.json"))
        names = ckpt["events/0"]["names"]
        committed_at_1 = names["1"]
        orphan = f"events__0__1__19990101T000000Z"  # old seq, unknown name
        shutil.copytree(rt / committed_at_1, rt / orphan)

        eng2 = QueryEngine()
        mgr2 = RealtimeTableDataManager(
            make_schema(), cfg, eng2.table("events"), str(tmp_path / "rt")
        )
        mgr2.start()
        try:
            assert 0 < _count(eng2) <= 180
            assert not (rt / orphan).exists()
            assert (rt / "_orphans" / orphan).exists()
        finally:
            mgr2.stop(commit_remaining=False)


class TestUpsertRestart:
    def test_rebuild_from_sealed_segments(self, tmp_path):
        """Restart recovery: add_segment over disk-loaded segments (no masks
        yet) must materialize validDocIds and hide stale rows."""
        from pinot_tpu.realtime.upsert import PartitionUpsertMetadataManager
        from pinot_tpu.storage.creator import build_segment
        from pinot_tpu.storage.segment import ImmutableSegment

        schema = make_schema(pk=True)
        cfg = TableConfig(table_name="events")
        s0_cols = {"user": ["alice", "bob"], "action": ["a", "b"],
                   "amount": [10, 20], "ts": [100, 100]}
        s1_cols = {"user": ["alice"], "action": ["c"], "amount": [99], "ts": [200]}
        build_segment(schema, s0_cols, str(tmp_path / "s0"), cfg, "s0")
        build_segment(schema, s1_cols, str(tmp_path / "s1"), cfg, "s1")
        s0 = ImmutableSegment(str(tmp_path / "s0"))
        s1 = ImmutableSegment(str(tmp_path / "s1"))

        upsert = PartitionUpsertMetadataManager("ts")
        for seg, cols in ((s0, s0_cols), (s1, s1_cols)):  # commit order
            upsert.add_segment(seg, [(u,) for u in cols["user"]], cols["ts"])

        eng = QueryEngine()
        eng.table("events").add_segment(s0)
        eng.table("events").add_segment(s1)
        r = eng.execute("SELECT COUNT(*) FROM events")
        assert r["resultTable"]["rows"][0][0] == 2  # alice deduped
        r = eng.execute("SELECT SUM(amount) FROM events WHERE user = 'alice'")
        assert r["resultTable"]["rows"][0][0] == 99
