"""Multi-replica realtime consumption + segment completion FSM.

Reference analogs: SegmentCompletionManager (committer election, HOLDING,
commit), LLRealtimeSegmentDataManager download-and-replace, and
RealtimeSegmentValidationManager repair.
"""

import time

import numpy as np

from pinot_tpu.cluster.registry import ClusterRegistry, InstanceInfo, Role
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import StreamConfig, TableConfig, TableType
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.realtime.completion import SegmentCompletionClient
from pinot_tpu.realtime.manager import RealtimeTableDataManager
from pinot_tpu.stream.memory_stream import TopicRegistry


def wait_until(cond, timeout=15.0, interval=0.03):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


def _schema():
    return Schema.build(
        name="events",
        dimensions=[("user", DataType.STRING)],
        metrics=[("amount", DataType.INT)],
        datetimes=[("ts", DataType.LONG)],
    )


def _cfg(topic, flush_rows):
    return TableConfig(
        table_name="events",
        table_type=TableType.REALTIME,
        stream=StreamConfig(
            stream_type="memory", topic=topic, decoder="json",
            segment_flush_threshold_rows=flush_rows,
            segment_flush_threshold_seconds=3600,
        ),
    )


def _count(eng):
    r = eng.execute("SELECT COUNT(*) FROM events")
    if r.get("exceptions"):
        return -1
    return r["resultTable"]["rows"][0][0]


def _replica(tmp_path, registry, cfg, instance_id, **kw):
    eng = QueryEngine(device_executor=None)
    mgr = RealtimeTableDataManager(
        _schema(), cfg, eng.table("events"), str(tmp_path / f"rt_{instance_id}"),
        completion_client=SegmentCompletionClient(
            registry, "events_REALTIME", instance_id, **kw
        ),
    )
    return eng, mgr


class TestCompletionFSM:
    def test_one_commit_per_sequence_losers_adopt(self, tmp_path):
        """Two replicas consume the same partition; each sequence is
        committed by exactly one replica, the other adopts — both serve
        every row exactly once."""
        TopicRegistry.delete("t_mr")
        topic = TopicRegistry.create("t_mr", 1)
        registry = ClusterRegistry()
        cfg = _cfg("t_mr", flush_rows=50)
        eng_a, mgr_a = _replica(tmp_path, registry, cfg, "A")
        eng_b, mgr_b = _replica(tmp_path, registry, cfg, "B")
        mgr_a.start(partitions=[0])
        mgr_b.start(partitions=[0])
        try:
            for wave in range(3):
                for i in range(60):
                    topic.publish_json(
                        {"user": f"u{i % 5}", "amount": 1, "ts": wave * 60 + i}
                    )
                assert wait_until(
                    lambda: _count(eng_a) == (wave + 1) * 60
                    and _count(eng_b) == (wave + 1) * 60
                ), (_count(eng_a), _count(eng_b))
            pa = mgr_a.partition_managers[0]
            pb = mgr_b.partition_managers[0]
            assert wait_until(lambda: pa.commits + pb.commits >= 3)
            # every committed sequence has exactly ONE committer; the other
            # replica adopted (or is still consuming behind)
            for seq in range(min(pa.commits + pb.commits, 3)):
                entry = registry.commit_entry("events_REALTIME", 0, seq)
                assert entry is not None and entry["state"] == "DONE", seq
                assert entry["committer"] in ("A", "B")
            assert pa.adoptions + pb.adoptions >= 1  # somebody held + adopted
            # exact-once on each replica
            r = eng_a.execute("SELECT user, COUNT(*) FROM events GROUP BY user ORDER BY user")
            assert [row[1] for row in r["resultTable"]["rows"]] == [36] * 5
        finally:
            mgr_a.stop(commit_remaining=False)
            mgr_b.stop(commit_remaining=False)

    def test_committer_death_takeover(self, tmp_path):
        """A claimed-but-dead committer goes stale; a holding replica takes
        over, commits its own rows, and ingestion continues — no loss."""
        TopicRegistry.delete("t_dead")
        topic = TopicRegistry.create("t_dead", 1)
        registry = ClusterRegistry()
        cfg = _cfg("t_dead", flush_rows=40)
        # the "dead server" claims sequence 0 and never finishes
        ghost = registry.try_claim_commit("events_REALTIME", 0, 0, "ghost", "ghost_seg")
        assert ghost["committer"] == "ghost"
        eng, mgr = _replica(tmp_path, registry, cfg, "B", stale_ms=300, poll_s=0.05)
        mgr.start(partitions=[0])
        try:
            for i in range(50):
                topic.publish_json({"user": "u", "amount": 1, "ts": i})
            # B flushes, holds behind ghost, takes over after stale_ms, commits
            assert wait_until(
                lambda: mgr.partition_managers[0].commits >= 1, timeout=20
            )
            entry = registry.commit_entry("events_REALTIME", 0, 0)
            assert entry["state"] == "DONE"
            assert entry["committer"] == "B"
            assert entry["segment"] != "ghost_seg"  # takeover re-recorded the name
            assert wait_until(lambda: _count(eng) == 50)
        finally:
            mgr.stop(commit_remaining=False)


class TestControllerRepair:
    def test_dead_consumer_partitions_reassigned(self, tmp_path):
        from pinot_tpu.controller.controller import Controller

        TopicRegistry.delete("t_repair")
        TopicRegistry.create("t_repair", 2)
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        now = int(time.time() * 1000)
        for sid in ("s1", "s2"):
            registry.register_instance(InstanceInfo(sid, Role.SERVER))
        cfg = _cfg("t_repair", flush_rows=100)
        cfg.replication = 2
        controller.add_table(cfg, _schema())
        pa = registry.partition_assignment("events_REALTIME")
        assert all(len(v) == 2 for v in pa.values())
        # s1 dies (heartbeat goes stale); a fresh s3 joins
        registry.register_instance(InstanceInfo("s3", Role.SERVER))
        dead = registry._tx_read(lambda s: s["instances"]["s1"])
        dead.last_heartbeat_ms = now - 120_000
        registry.register_instance(InstanceInfo("s2", Role.SERVER))  # fresh hb
        registry._tx(lambda s: s["instances"].__setitem__("s1", dead))
        changed = controller.run_realtime_repair()
        assert "events_REALTIME" in changed
        pa = registry.partition_assignment("events_REALTIME")
        for insts in pa.values():
            assert "s1" not in insts
            assert len(insts) == 2 and set(insts) <= {"s2", "s3"}
