"""Server-side group trim, query scheduler admission, segment refcounts.

Reference analogs: TableResizer / trimSize semantics, QueryScheduler +
BoundedAccountingExecutor rejection, TableDataManager acquire/release
with deferred teardown.
"""

import threading
import time

import numpy as np
import pytest

from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.engine.engine import QueryEngine, TableDataManager
from pinot_tpu.engine.reduce import trim_group_by
from pinot_tpu.engine.scheduler import QueryScheduler, SchedulerSaturated
from pinot_tpu.query.optimizer import optimize_query
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.sql.compiler import compile_query
from pinot_tpu.storage.creator import build_segment


def wait_until(cond, timeout=15.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


def _seg(tmp_path, name, n=4000, seed=0):
    schema = Schema.build(
        name="s",
        dimensions=[("k", DataType.STRING)],
        metrics=[("v", DataType.LONG)],
    )
    rng = np.random.default_rng(seed)
    cols = {
        "k": np.array([f"key{i:05d}" for i in rng.integers(0, 2000, n)]),
        "v": rng.integers(1, 100, n).astype(np.int64),
    }
    d = str(tmp_path / name)
    build_segment(schema, cols, d, TableConfig(table_name="s"), name)
    from pinot_tpu.storage.segment import ImmutableSegment

    return schema, cols, ImmutableSegment(d)


class TestGroupTrim:
    def _merged(self, tmp_path, sql):
        schema, cols, seg = _seg(tmp_path, "t0")
        engine = QueryEngine(device_executor=None)
        q = optimize_query(compile_query(sql))
        return q, engine.execute_segments(q, [seg]), cols

    def test_trim_bounds_groups_and_keeps_topk_exact(self, tmp_path):
        sql = ("SELECT k, SUM(v) FROM s GROUP BY k "
               "ORDER BY SUM(v) DESC LIMIT 4")
        q, merged, cols = self._merged(tmp_path, sql)
        n_full = len(merged.group_keys[0])
        assert n_full > 100
        trimmed = trim_group_by(q, merged, min_trim_size=50)
        assert len(trimmed.group_keys[0]) == 50
        # top-LIMIT result identical to the untrimmed reduce
        from pinot_tpu.engine.reduce import finalize

        assert finalize(q, trimmed).rows == finalize(q, merged).rows

    def test_no_trim_without_order_by_or_with_having(self, tmp_path):
        q, merged, _ = self._merged(
            tmp_path, "SELECT k, SUM(v) FROM s GROUP BY k LIMIT 4")
        assert trim_group_by(q, merged, min_trim_size=10) is merged
        q2, merged2, _ = self._merged(
            tmp_path,
            "SELECT k, SUM(v) FROM s GROUP BY k HAVING SUM(v) > 50 "
            "ORDER BY SUM(v) DESC LIMIT 4",
        )
        assert trim_group_by(q2, merged2, min_trim_size=10) is merged2

    def test_trim_respects_5x_headroom(self, tmp_path):
        q, merged, _ = self._merged(
            tmp_path,
            "SELECT k, SUM(v) FROM s GROUP BY k ORDER BY SUM(v) DESC LIMIT 30",
        )
        trimmed = trim_group_by(q, merged, min_trim_size=10)
        assert len(trimmed.group_keys[0]) == 150  # 5 * limit > min_trim

    def test_cluster_trimmed_group_by_matches_oracle(self, tmp_path):
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        servers = [
            ServerInstance(f"server_{i}", registry, str(tmp_path / f"s{i}"),
                           device_executor=None, group_trim_size=40)
            for i in range(2)
        ]
        for s in servers:
            s.start()
        from pinot_tpu.broker.broker import Broker

        broker = Broker(registry, timeout_s=10.0)
        try:
            schema = Schema.build(
                name="sales",
                dimensions=[("k", DataType.STRING)],
                metrics=[("v", DataType.LONG)],
            )
            cfg = TableConfig(table_name="sales", replication=1)
            controller.add_table(cfg, schema)
            # Per-group values identical in every segment, so local order ==
            # global order and the (by-design approximate) trim must return
            # the exact global top-K. 500 groups >> trim size 40.
            for i in range(4):
                cols = {
                    "k": np.array([f"g{j:04d}" for j in range(500)]),
                    "v": np.arange(500, dtype=np.int64),
                }
                d = str(tmp_path / f"up{i}")
                build_segment(schema, cols, d, cfg, f"sales_{i}")
                controller.upload_segment("sales", d)
            assert wait_until(
                lambda: len(registry.external_view("sales_OFFLINE")) == 4)
            r = broker.execute(
                "SELECT k, SUM(v) FROM sales GROUP BY k "
                "ORDER BY SUM(v) DESC, k ASC LIMIT 5"
            )
            assert not r.get("exceptions"), r
            want = [(f"g{j:04d}", 4.0 * j) for j in range(499, 494, -1)]
            assert [tuple(row) for row in r["resultTable"]["rows"]] == want
        finally:
            broker.close()
            for s in servers:
                s.stop()


class TestQueryScheduler:
    def test_rejects_when_saturated(self):
        sched = QueryScheduler(max_concurrent=1, max_queued=1,
                               queue_timeout_s=5.0)
        release = threading.Event()
        started = threading.Event()
        results = []

        def slow():
            started.set()
            release.wait(10)
            return "slow-done"

        t1 = threading.Thread(
            target=lambda: results.append(sched.run(slow)))
        t1.start()
        assert started.wait(5)
        # one waiter fits in the queue...
        t2 = threading.Thread(
            target=lambda: results.append(sched.run(lambda: "queued-done")))
        t2.start()
        assert wait_until(lambda: sched._waiting == 1, timeout=5)
        # ...the next is rejected immediately
        with pytest.raises(SchedulerSaturated):
            sched.run(lambda: "never")
        assert sched.num_rejected == 1
        release.set()
        t1.join(5)
        t2.join(5)
        assert sorted(results) == ["queued-done", "slow-done"]
        assert sched.num_executed == 2

    def test_slot_wait_timeout(self):
        sched = QueryScheduler(max_concurrent=1, max_queued=4,
                               queue_timeout_s=0.05)
        release = threading.Event()
        t = threading.Thread(
            target=lambda: sched.run(lambda: release.wait(10)))
        t.start()
        assert wait_until(lambda: sched.num_executed == 1, timeout=5)
        with pytest.raises(SchedulerSaturated, match="slot"):
            sched.run(lambda: "never")
        release.set()
        t.join(5)


class TestSegmentRefcounts:
    def test_remove_defers_unload_until_release(self, tmp_path):
        _, _, seg = _seg(tmp_path, "rc0", n=100)
        tdm = TableDataManager("t")
        unloaded = []
        tdm.on_unload = unloaded.append
        tdm.add_segment(seg)
        held = tdm.acquire()
        assert held == [seg]
        tdm.remove_segment(seg.name)
        assert seg.name not in tdm.segments  # no new queries see it
        assert unloaded == []                # but teardown is deferred
        # the in-flight query can still read data
        assert len(np.asarray(seg.values("k"))) == 100
        tdm.release(held)
        assert unloaded == [seg]

    def test_unreferenced_remove_unloads_immediately(self, tmp_path):
        _, _, seg = _seg(tmp_path, "rc1", n=50)
        tdm = TableDataManager("t")
        unloaded = []
        tdm.on_unload = unloaded.append
        tdm.add_segment(seg)
        tdm.remove_segment(seg.name)
        assert unloaded == [seg]

    def test_server_downloads_local_copy_and_cleans_up(self, tmp_path):
        import os

        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        server = ServerInstance("server_0", registry, str(tmp_path / "s0"),
                                device_executor=None)
        server.start()
        from pinot_tpu.broker.broker import Broker

        broker = Broker(registry, timeout_s=10.0)
        try:
            schema = Schema.build(
                name="sales",
                dimensions=[("k", DataType.STRING)],
                metrics=[("v", DataType.LONG)],
            )
            cfg = TableConfig(table_name="sales")
            controller.add_table(cfg, schema)
            d = str(tmp_path / "up")
            build_segment(schema, {"k": ["a", "b"], "v": [1, 2]}, d, cfg, "seg0")
            controller.upload_segment("sales", d)
            import glob

            pattern = os.path.join(str(tmp_path / "s0"), "segments",
                                   "sales_OFFLINE", "seg0*")
            assert wait_until(lambda: glob.glob(pattern), timeout=30)
            # the local copy lands before the external-view publish at the
            # end of the same sync tick: wait for routability too
            assert wait_until(
                lambda: len(registry.external_view("sales_OFFLINE")) == 1,
                timeout=30)
            r = broker.execute("SELECT SUM(v) FROM sales")
            assert r["resultTable"]["rows"] == [[3]]
            # delete: registry entry goes, server unloads, local copy removed
            controller.delete_segment("sales", "seg0")
            assert wait_until(lambda: not glob.glob(pattern), timeout=30)
        finally:
            broker.close()
            server.stop()
