"""Broker fleet front door (ISSUE 18): discovery, drain/rotation,
cross-broker cache coherence, fleet-fair admission gossip, and streaming
result delivery.

Reference analogs: BrokerStarter's Helix BROKER-resource registration
(clients discover the fleet through ZK), BrokerResourceOnlineOfflineState
drain semantics, and the gRPC/cursor streaming result delivery — here
over the registry's existing heartbeat plumbing plus HTTP chunked NDJSON.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from pinot_tpu import client as pt_client
from pinot_tpu.broker.admission import TenantAdmissionController
from pinot_tpu.broker.broker import Broker
from pinot_tpu.broker.fleet import (BrokerFleetMember, discover_broker_urls,
                                    live_brokers)
from pinot_tpu.broker.http_api import BrokerHttpServer
from pinot_tpu.cluster.registry import ClusterRegistry, Role
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import StreamConfig, TableConfig, TableType
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.stream.memory_stream import TopicRegistry


def wait_until(cond, timeout=10.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def cluster(tmp_path):
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "deepstore"))
    server = ServerInstance("server_0", registry, str(tmp_path / "srv0"),
                            device_executor=None)
    server.start()
    yield registry, controller, server
    try:
        server.stop()
    except Exception:
        pass


def _offline_table(tmp_path, controller, name="sales", n_segments=2,
                   rows=3000):
    schema = Schema.build(
        name=name,
        dimensions=[("region", DataType.STRING)],
        metrics=[("amount", DataType.INT)],
    )
    cfg = TableConfig(table_name=name)
    controller.add_table(cfg, schema)
    rng = np.random.default_rng(42)
    for i in range(n_segments):
        cols = {
            "region": np.array(["na", "eu", "apac"])[
                rng.integers(0, 3, rows)],
            "amount": rng.integers(1, 500, rows).astype(np.int32),
        }
        d = str(tmp_path / f"{name}_up{i}")
        build_segment(schema, cols, d, cfg, f"{name}_s{i}")
        controller.upload_segment(name, d)


def _wait_served(broker, sql, timeout=15.0):
    def ok():
        r = broker.execute(sql)
        return not r.get("exceptions") and not r.get("partialResult")
    assert wait_until(ok, timeout=timeout)


class TestFleetMembership:
    def test_register_discover_drain_deregister(self, cluster, tmp_path):
        registry, controller, server = cluster
        bks = [Broker(registry, broker_id=f"bk_{i}") for i in range(2)]
        fleets = [
            BrokerFleetMember(registry, bks[i],
                              http_url=f"http://127.0.0.1:{8100 + i}",
                              heartbeat_interval_ms=100).start()
            for i in range(2)
        ]
        try:
            assert wait_until(
                lambda: len(discover_broker_urls(registry)) == 2)
            assert sorted(discover_broker_urls(registry)) == [
                "http://127.0.0.1:8100", "http://127.0.0.1:8101"]

            # drain publishes immediately: discovery drops the member
            # without waiting a heartbeat, liveness keeps it visible
            fleets[0].drain()
            assert discover_broker_urls(registry) == \
                ["http://127.0.0.1:8101"]
            assert len(live_brokers(registry, include_draining=True)) == 2
            assert bks[0].execute("SELECT 1").get("brokerDraining")

            fleets[0].undrain()
            assert len(discover_broker_urls(registry)) == 2

            # stop() deregisters cleanly — no TTL wait
            fleets[1].stop()
            fleets = fleets[:1]
            assert discover_broker_urls(registry) == \
                ["http://127.0.0.1:8100"]
        finally:
            for fm in fleets:
                fm.stop()
            for bk in bks:
                bk.close()

    def test_heartbeat_stats_and_controller_endpoint(self, cluster,
                                                     tmp_path):
        from pinot_tpu.controller.http_api import ControllerHttpServer

        registry, controller, server = cluster
        _offline_table(tmp_path, controller)
        bk = Broker(registry, broker_id="bk_stats", result_cache=True)
        fm = BrokerFleetMember(registry, bk, http_url="http://x:1",
                               heartbeat_interval_ms=100).start()
        http = ControllerHttpServer(registry)
        http.start()
        try:
            _wait_served(bk, "SELECT COUNT(*) FROM sales")
            bk.execute("SELECT COUNT(*) FROM sales")  # cache hit
            # counters surface in the registry heartbeat...
            def stats():
                infos = {i.instance_id: i
                         for i in registry.instances(Role.BROKER)}
                return (infos.get("bk_stats").stats
                        if "bk_stats" in infos else {})
            assert wait_until(lambda: stats().get("queries", 0) >= 2)
            # the hit counter rides the NEXT heartbeat tick
            assert wait_until(lambda: stats().get("cacheHits", 0) >= 1)
            # ...and through the controller's GET /brokers
            with urllib.request.urlopen(http.url + "/brokers",
                                        timeout=5) as resp:
                doc = json.loads(resp.read())
            rec = doc["brokers"]["bk_stats"]
            assert rec["live"] and not rec["draining"]
            assert rec["url"] == "http://x:1"
            assert rec["queries"] >= 2
        finally:
            http.stop()
            fm.stop()
            bk.close()


class TestCrossBrokerCoherence:
    def test_ingest_via_a_invalidates_b_within_heartbeat(self, cluster,
                                                         tmp_path):
        """Two cache-enabled brokers; realtime ingest lands while B holds
        a cached result. B's next read must NOT serve the stale count —
        the per-table freshness epoch rides server heartbeats to every
        broker's epoch view, so coherence needs no cross-broker
        invalidation channel."""
        registry, controller, server = cluster
        TopicRegistry.delete("coh")
        topic = TopicRegistry.create("coh", 1)
        schema = Schema.build(
            name="coh", dimensions=[("k", DataType.STRING)],
            metrics=[("n", DataType.INT)])
        cfg = TableConfig(
            table_name="coh", table_type=TableType.REALTIME,
            stream=StreamConfig(
                stream_type="memory", topic="coh", decoder="json",
                segment_flush_threshold_rows=10_000,
                segment_flush_threshold_seconds=3600,
            ),
        )
        controller.add_table(cfg, schema)
        for i in range(50):
            topic.publish_json({"k": f"k{i % 5}", "n": 1})

        bk_a = Broker(registry, broker_id="coh_a", result_cache=True)
        bk_b = Broker(registry, broker_id="coh_b", result_cache=True)
        fleets = [BrokerFleetMember(registry, bk,
                                    heartbeat_interval_ms=100).start()
                  for bk in (bk_a, bk_b)]
        sql = "SELECT COUNT(*) FROM coh"

        def count(bk):
            r = bk.execute(sql)
            if r.get("exceptions"):
                return -1
            return r["resultTable"]["rows"][0][0]

        try:
            assert wait_until(lambda: count(bk_a) == 50, timeout=15)
            assert wait_until(lambda: count(bk_b) == 50)
            # both caches hot on the same result
            assert bk_a.execute(sql).get("resultCacheHit")
            assert bk_b.execute(sql).get("resultCacheHit")

            # concurrent reads on B while ingest flows through the stream
            stale_served = [0]
            stop = threading.Event()

            def hammer_b():
                while not stop.is_set():
                    r = bk_b.execute(sql)
                    n = r["resultTable"]["rows"][0][0]
                    if r.get("resultCacheHit") and n not in (50, 80):
                        stale_served[0] += 1
                    time.sleep(0.01)

            t = threading.Thread(target=hammer_b)
            t.start()
            for i in range(30):
                topic.publish_json({"k": f"k{i % 5}", "n": 1})
            # B converges to the new count within (consume + heartbeat)
            assert wait_until(lambda: count(bk_b) == 80, timeout=15)
            stop.set()
            t.join()
            # no cache hit on B ever served a count that was neither the
            # pre- nor post-ingest value
            assert stale_served[0] == 0
            # and the fresh result re-caches: B hits again at 80
            assert wait_until(
                lambda: bk_b.execute(sql).get("resultCacheHit")
                and count(bk_b) == 80)
            assert count(bk_a) == 80
        finally:
            stop.set()
            for fm in fleets:
                fm.stop()
            bk_a.close()
            bk_b.close()
            TopicRegistry.delete("coh")


class TestAdmissionGossip:
    def test_observe_peer_spend_debits_local_bucket(self):
        adm = TenantAdmissionController(rate_qps=5.0, burst=4.0)
        # local bucket starts at full burst: 4 admits pass
        for _ in range(4):
            assert adm.try_admit("t1", "dashboard").admitted
        assert not adm.try_admit("t1", "dashboard").admitted
        # peer restart: counter going BACKWARD is treated as fresh spend,
        # not a negative delta
        adm2 = TenantAdmissionController(rate_qps=5.0, burst=4.0)
        adm2.observe_peer_spend("peer", {"t1": 100.0})
        adm2.observe_peer_spend("peer", {"t1": 2.0})
        snap = adm2._peer_spend_seen["peer"]
        assert snap["t1"] == 2.0
        # a peer's spend empties the local bucket too (shared budget)
        adm3 = TenantAdmissionController(rate_qps=5.0, burst=4.0)
        adm3.observe_peer_spend("peer", {"t2": 4.0})
        assert not adm3.try_admit("t2", "dashboard").admitted
        adm3.forget_peer("peer")
        assert "peer" not in adm3._peer_spend_seen

    def test_fleet_shares_one_tenant_budget(self, cluster, tmp_path):
        """Spend on broker A propagates through heartbeat gossip and
        empties the same tenant's bucket on broker B."""
        registry, controller, server = cluster
        _offline_table(tmp_path, controller, name="adm", n_segments=1,
                       rows=500)
        bks = [Broker(registry, broker_id=f"adm_{i}",
                      admission=TenantAdmissionController(
                          rate_qps=2.0, burst=6.0))
               for i in range(2)]
        fleets = [BrokerFleetMember(registry, bk,
                                    heartbeat_interval_ms=100).start()
                  for bk in bks]
        sql = "SELECT COUNT(*) FROM adm"
        try:
            _wait_served(bks[0], sql)
            # burn tenant X's burst on broker A only
            for _ in range(8):
                bks[0].execute(sql, principal="tx")
            # within a couple of heartbeats, broker B has observed A's
            # spend and refuses the same tenant despite never serving it
            def b_rejects():
                r = bks[1].execute(sql, principal="tx")
                excs = r.get("exceptions") or []
                return bool(excs) and excs[0].get("errorCode") == 429
            assert wait_until(b_rejects, timeout=5)
            # a different tenant still has its own full budget on B
            r = bks[1].execute(sql, principal="ty")
            assert not r.get("exceptions")
        finally:
            for fm in fleets:
                fm.stop()
            for bk in bks:
                bk.close()


class TestClientRotation:
    def test_retry_policy_single_source(self):
        assert pt_client.retry_after_s("2") == 2.0
        assert pt_client.retry_after_s(99) == pt_client.MAX_RETRY_AFTER_S
        assert pt_client.retry_after_s(0.0) == 0.05
        assert pt_client.retry_after_s("nope") == 0.5
        assert pt_client.is_quota_rejection(
            {"exceptions": [{"errorCode": 429}]})
        assert not pt_client.is_quota_rejection(
            {"exceptions": [{"errorCode": 429}, {"errorCode": 450}]})
        assert not pt_client.is_quota_rejection({"exceptions": []})
        # the in-process and HTTP paths share the ONE module-level policy
        assert pt_client.Connection._retry_after_s is pt_client.retry_after_s
        assert pt_client.Connection._is_quota_rejection \
            is pt_client.is_quota_rejection

    def test_drain_mid_run_rotates_with_zero_errors(self, cluster,
                                                    tmp_path):
        registry, controller, server = cluster
        _offline_table(tmp_path, controller, name="rot")
        bks = [Broker(registry, broker_id=f"rot_{i}") for i in range(2)]
        https = [BrokerHttpServer(bk, port=0) for bk in bks]
        for h in https:
            h.start()
        fleets = [BrokerFleetMember(registry, bks[i], http_url=https[i].url,
                                    heartbeat_interval_ms=100).start()
                  for i in range(2)]
        try:
            _wait_served(bks[0], "SELECT COUNT(*) FROM rot")
            conn = pt_client.connect(
                broker_urls=[h.url for h in https], timeout_s=10.0)
            cur = conn.cursor()
            served_by = set()
            for k in range(30):
                if k == 10:
                    fleets[0].drain()  # broker 0 starts 503ing mid-run
                cur.execute("SELECT COUNT(*) FROM rot")
                assert cur.fetchone() == (6000,)
                served_by.add(cur.stats.get("brokerId"))
            # pre-drain traffic reached both; post-drain all landed on 1
            assert served_by == {"rot_0", "rot_1"}
            assert bks[1].queries_served > bks[0].queries_served

            # drain the whole fleet: bounded rotation fails typed
            fleets[1].drain()
            with pytest.raises(pt_client.NoLiveBrokersError):
                cur.execute("SELECT COUNT(*) FROM rot")
            conn.close()
        finally:
            for fm in fleets:
                fm.stop()
            for h in https:
                h.stop()
            for bk in bks:
                bk.close()

    def test_registry_discovery_connection(self, cluster, tmp_path):
        registry, controller, server = cluster
        _offline_table(tmp_path, controller, name="disc")
        bk = Broker(registry, broker_id="disc_0")
        http = BrokerHttpServer(bk, port=0)
        http.start()
        fm = BrokerFleetMember(registry, bk, http_url=http.url,
                               heartbeat_interval_ms=100).start()
        try:
            _wait_served(bk, "SELECT COUNT(*) FROM disc")
            assert wait_until(
                lambda: discover_broker_urls(registry) == [http.url])
            conn = pt_client.connect(registry=registry, discover=True)
            cur = conn.cursor()
            cur.execute("SELECT COUNT(*) FROM disc")
            assert cur.fetchone() == (6000,)
            conn.close()
        finally:
            fm.stop()
            http.stop()
            bk.close()


class TestStreaming:
    def _rows_via_stream(self, chunks):
        rows, final, schema = [], None, None
        for c in chunks:
            if c.get("type") == "schema":
                schema = c
            elif c.get("type") == "rows":
                rows.extend(tuple(r) for r in c["rows"])
            elif c.get("type") == "final":
                final = c
        return schema, rows, final

    def test_inprocess_stream_parity_and_order(self, cluster, tmp_path):
        registry, controller, server = cluster
        _offline_table(tmp_path, controller, name="st", n_segments=2,
                       rows=4000)
        bk = Broker(registry, broker_id="st_bk")
        try:
            _wait_served(bk, "SELECT COUNT(*) FROM st")
            sql = "SELECT region, amount FROM st LIMIT 8000"
            buffered = bk.execute(sql)
            schema, rows, final = self._rows_via_stream(
                bk.execute_stream(sql, chunk_rows=1000))
            assert schema["columnNames"] == \
                buffered["resultTable"]["dataSchema"]["columnNames"]
            assert final.get("streamed") is True
            assert not final.get("exceptions")
            assert final["numRowsStreamed"] == 8000
            assert rows == [tuple(r) for r in
                            buffered["resultTable"]["rows"]]
            # brokerId + querylog stamping covers the streaming path too
            assert final.get("brokerId") == "st_bk"

            # offset/limit trim happens broker-side, identically
            sql2 = "SELECT region, amount FROM st LIMIT 100, 37"
            b2 = bk.execute(sql2)
            _, rows2, f2 = self._rows_via_stream(bk.execute_stream(sql2))
            assert rows2 == [tuple(r) for r in
                             b2["resultTable"]["rows"]]
            assert len(rows2) == 37
        finally:
            bk.close()

    def test_nonstreamable_falls_back_buffered(self, cluster, tmp_path):
        registry, controller, server = cluster
        _offline_table(tmp_path, controller, name="agg", n_segments=1,
                       rows=2000)
        bk = Broker(registry, broker_id="agg_bk")
        try:
            _wait_served(bk, "SELECT COUNT(*) FROM agg")
            sql = ("SELECT region, COUNT(*) FROM agg GROUP BY region "
                   "ORDER BY region")
            buffered = bk.execute(sql)
            schema, rows, final = self._rows_via_stream(
                bk.execute_stream(sql))
            assert rows == [tuple(r) for r in
                            buffered["resultTable"]["rows"]]
            assert not final.get("exceptions")
            # the universal cursor API: same chunk shape, not the true
            # server-streaming path
            assert not final.get("streamed")
        finally:
            bk.close()

    def test_http_ndjson_stream_and_client_cursor(self, cluster, tmp_path):
        registry, controller, server = cluster
        _offline_table(tmp_path, controller, name="hs", n_segments=2,
                       rows=3000)
        bk = Broker(registry, broker_id="hs_bk")
        http = BrokerHttpServer(bk, port=0)
        http.start()
        try:
            _wait_served(bk, "SELECT COUNT(*) FROM hs")
            sql = "SELECT region, amount FROM hs LIMIT 6000"
            # raw wire: chunked transfer, one JSON object per line
            req = urllib.request.Request(
                http.url + "/query/sql/stream",
                data=json.dumps({"sql": sql}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.headers.get("Content-Type") == \
                    "application/x-ndjson"
                lines = [json.loads(ln) for ln in resp if ln.strip()]
            assert lines[0]["type"] == "schema"
            assert lines[-1]["type"] == "final"
            n_wire = sum(len(c.get("rows") or ()) for c in lines)
            assert n_wire == 6000

            # DB-API streaming cursor against the same endpoint
            conn = pt_client.connect(http.url, timeout_s=10.0)
            cur = conn.cursor()
            cur.execute_stream(sql)
            assert [d[0] for d in cur.description] == ["region", "amount"]
            streamed = cur.fetchall()
            assert cur.stats.get("numRowsStreamed") == 6000
            cur.execute(sql)
            assert streamed == cur.fetchall()
            conn.close()
        finally:
            http.stop()
            bk.close()

    def test_stream_open_rotates_off_draining_broker(self, cluster,
                                                     tmp_path):
        registry, controller, server = cluster
        _offline_table(tmp_path, controller, name="sr", n_segments=1,
                       rows=1000)
        bks = [Broker(registry, broker_id=f"sr_{i}") for i in range(2)]
        try:
            _wait_served(bks[0], "SELECT COUNT(*) FROM sr")
            bks[0].draining = True
            conn = pt_client.connect(brokers=list(bks), timeout_s=10.0)
            cur = conn.cursor()
            for _ in range(4):  # every rotation start lands on sr_1
                cur.execute_stream("SELECT region FROM sr LIMIT 10")
                assert len(cur.fetchall()) == 10
                assert cur.stats.get("brokerId") == "sr_1"
            conn.close()
        finally:
            for bk in bks:
                bk.close()


class TestQuerylogFleetMerge:
    def test_multi_file_merge_with_broker_breakdown(self, tmp_path):
        from pinot_tpu.tools import querylog as ql

        def entry(bid, ms, exc=None):
            return {"brokerId": bid, "timeUsedMs": ms, "table": "t",
                    "exceptions": exc or []}

        a = tmp_path / "a.jsonl"
        b = tmp_path / "b.jsonl"
        a.write_text("\n".join(json.dumps(entry("bk_a", 10.0))
                               for _ in range(4)))
        b.write_text("\n".join(
            [json.dumps(entry("bk_b", 30.0)) for _ in range(2)]
            + [json.dumps(entry("bk_b", 50.0,
                                [{"errorCode": 450, "message": "x"}]))]))
        entries = ql.load(str(a)) + ql.load(str(b))
        summary = ql.summarize(entries)
        assert summary["queries"] == 7
        assert summary["brokers"]["bk_a"] == {
            "queries": 4, "errors": 0, "p50Ms": 10.0, "p90Ms": 10.0}
        assert summary["brokers"]["bk_b"]["queries"] == 3
        assert summary["brokers"]["bk_b"]["errors"] == 1
        # CLI accepts multiple paths
        assert ql.main([str(a), str(b), "--json"]) == 0
