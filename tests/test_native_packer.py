"""Native bit-packing codec + packed forward indexes.

Reference analogs: PinotDataBitSetTest, FixedBitSVForwardIndexTest —
roundtrip across bit widths, format parity between native and fallback,
and query equality for packed vs plain segments.
"""

import os

import numpy as np
import pytest

from pinot_tpu import native
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment


class TestCodec:
    def test_native_library_builds(self):
        # the dev/CI image ships g++; environments without it use the
        # numpy fallback, but HERE the native path must be exercised
        assert native.native_available()

    @pytest.mark.parametrize("bits", [1, 2, 3, 5, 7, 8, 11, 13, 16])
    def test_roundtrip(self, bits):
        rng = np.random.default_rng(bits)
        ids = rng.integers(0, 1 << bits, 10_001).astype(np.int32)
        buf = native.pack(ids, bits)
        assert len(buf) == native.packed_size(len(ids), bits)
        out = native.unpack(buf, len(ids), bits)
        np.testing.assert_array_equal(out, ids)

    def test_native_and_numpy_formats_identical(self):
        rng = np.random.default_rng(0)
        for bits in (1, 6, 12):
            ids = rng.integers(0, 1 << bits, 4097).astype(np.int32)
            nat = native.pack(ids, bits)
            fall = native._pack_np(ids, bits,
                                   np.zeros(native.packed_size(len(ids), bits),
                                            dtype=np.uint8))
            np.testing.assert_array_equal(nat, fall)
            np.testing.assert_array_equal(
                native._unpack_np(nat, len(ids), bits),
                native.unpack(nat, len(ids), bits),
            )

    def test_empty_and_single(self):
        assert len(native.pack(np.empty(0, np.int32), 4)) == 0
        buf = native.pack(np.array([5], np.int32), 3)
        assert native.unpack(buf, 1, 3).tolist() == [5]

    def test_bits_needed(self):
        assert native.bits_needed(0) == 1
        assert native.bits_needed(1) == 1
        assert native.bits_needed(2) == 1
        assert native.bits_needed(3) == 2
        assert native.bits_needed(256) == 8
        assert native.bits_needed(257) == 9


class TestPackedSegments:
    def _build(self, tmp_path, packed: bool):
        schema = Schema.build(
            name="t",
            dimensions=[("city", DataType.STRING), ("code", DataType.INT)],
            metrics=[("v", DataType.LONG)],
        )
        cfg = TableConfig(
            table_name="t",
            indexing=IndexingConfig(
                enable_bit_packing=packed,
                inverted_index_columns=["city"],
            ),
        )
        rng = np.random.default_rng(3)
        n = 20_000
        cols = {
            "city": np.array([f"c{j}" for j in range(37)])[rng.integers(0, 37, n)],
            "code": rng.integers(0, 500, n).astype(np.int32),
            "v": rng.integers(0, 1000, n).astype(np.int64),
        }
        d = str(tmp_path / ("packed" if packed else "plain"))
        return build_segment(schema, cols, d, cfg, "s0"), d

    def test_packed_matches_plain_and_is_smaller(self, tmp_path):
        plain, dp = self._build(tmp_path, packed=False)
        packed, dq = self._build(tmp_path, packed=True)
        meta = packed.column_metadata("city")
        assert meta.packed_bits == 6  # 37 values -> 6 bits
        assert packed.column_metadata("code").packed_bits == 9
        assert packed.column_metadata("v").packed_bits is None  # RAW metric
        assert os.path.getsize(os.path.join(dq, "city.fwdpacked.bin")) \
            < os.path.getsize(os.path.join(dp, "city.fwd.npy")) / 4
        np.testing.assert_array_equal(
            np.asarray(packed.forward("city")), np.asarray(plain.forward("city")))

        eng_plain = QueryEngine(device_executor=None)
        eng_plain.add_segment("t", plain)
        eng_packed = QueryEngine(device_executor=None)
        eng_packed.add_segment("t", ImmutableSegment(dq))
        for sql in (
            "SELECT COUNT(*), SUM(v) FROM t",
            "SELECT city, SUM(v) FROM t WHERE code >= 250 "
            "GROUP BY city ORDER BY city LIMIT 50",
            "SELECT COUNT(*) FROM t WHERE city = 'c7'",  # inverted-index path
        ):
            rp = eng_plain.execute(sql)
            rq = eng_packed.execute(sql)
            assert not rp.get("exceptions") and not rq.get("exceptions")
            assert rp["resultTable"]["rows"] == rq["resultTable"]["rows"], sql


class TestChunkCompression:
    """Chunked zlib raw forward indexes (io/compression analog)."""

    def test_roundtrip_native_and_fallback(self):
        rng = np.random.default_rng(11)
        data = rng.integers(0, 100, 300_000).astype(np.int64)  # compressible
        blob, offs = native.compress_chunks(data)
        total = data.nbytes
        out = native.decompress_chunks(blob, offs, total).view(np.int64)
        np.testing.assert_array_equal(out, data)
        # stdlib-zlib fallback reads the same bytes
        import pinot_tpu.native as nat

        lib, tried = nat._lib, nat._lib_tried
        nat._lib, nat._lib_tried = None, True
        try:
            out2 = native.decompress_chunks(blob, offs, total).view(np.int64)
        finally:
            nat._lib, nat._lib_tried = lib, tried
        np.testing.assert_array_equal(out2, data)

    def test_empty(self):
        blob, offs = native.compress_chunks(np.empty(0, dtype=np.float64))
        assert len(native.decompress_chunks(blob, offs, 0)) == 0

    def test_corrupt_blob_raises(self):
        data = np.arange(1000, dtype=np.int32)
        blob, offs = native.compress_chunks(data)
        bad = blob.copy()
        bad[4:12] = 0
        with pytest.raises(ValueError, match="corrupt"):
            native.decompress_chunks(bad, offs, data.nbytes)

    @pytest.mark.parametrize("codec", ["zlib", "zstd", "lz4"])
    def test_all_codecs_roundtrip_native_and_fallback(self, codec):
        """Per-codec round-trip (reference ChunkCompressionType): native
        loop AND pure-python fallback must read the same bytes."""
        rng = np.random.default_rng(13)
        data = rng.integers(0, 64, 700_000).astype(np.int32)  # 3 chunks
        blob, offs = native.compress_chunks(data, codec=codec)
        total = data.nbytes
        out = native.decompress_chunks(blob, offs, total, codec=codec)
        np.testing.assert_array_equal(out.view(np.int32), data)
        import pinot_tpu.native as nat

        lib, tried = nat._lib, nat._lib_tried
        nat._lib, nat._lib_tried = None, True
        try:
            out2 = native.decompress_chunks(blob, offs, total, codec=codec)
            # and python-compressed bytes load through the native loop
            blob_py, offs_py = native.compress_chunks(data, codec=codec)
        finally:
            nat._lib, nat._lib_tried = lib, tried
        np.testing.assert_array_equal(out2.view(np.int32), data)
        out3 = native.decompress_chunks(blob_py, offs_py, total, codec=codec)
        np.testing.assert_array_equal(out3.view(np.int32), data)

    def test_lz4_python_fallback_format_is_valid(self):
        """The literal-only python LZ4 encoder must produce blocks the
        NATIVE decoder accepts (cross-compat both directions)."""
        if not native.native_available():
            pytest.skip("needs the native library")
        rng = np.random.default_rng(17)
        raw = rng.integers(0, 255, 10_000).astype(np.uint8).tobytes()
        py_block = native._lz4_compress_py(raw)
        assert native._lz4_decompress_py(py_block, len(raw)) == raw
        blob = np.frombuffer(py_block, dtype=np.uint8)
        offs = np.array([0, len(py_block)], dtype=np.int64)
        out = native.decompress_chunks(blob, offs, len(raw), codec="lz4")
        assert out.tobytes() == raw

    @pytest.mark.parametrize("codec", ["zstd", "lz4"])
    def test_codec_segment_roundtrip(self, tmp_path, codec):
        schema = Schema.build(
            name="t", dimensions=[("k", DataType.STRING)],
            metrics=[("v", DataType.LONG)])
        rng = np.random.default_rng(7)
        n = 150_000
        cols = {"k": np.array([f"c{j}" for j in rng.integers(0, 20, n)]),
                "v": rng.integers(0, 50, n).astype(np.int64)}
        d = str(tmp_path / codec)
        build_segment(schema, cols, d, TableConfig(
            table_name="t",
            indexing=IndexingConfig(compression_codec={"v": codec})), "s0")
        seg = ImmutableSegment(d)
        assert seg.column_metadata("v").compression == codec
        np.testing.assert_array_equal(np.asarray(seg.forward("v")), cols["v"])
        eng = QueryEngine(device_executor=None)
        eng.add_segment("t", seg)
        r = eng.execute("SELECT SUM(v) FROM t")
        assert r["resultTable"]["rows"][0][0] == float(cols["v"].sum())

    def test_compressed_segment_matches_plain_and_is_smaller(self, tmp_path):
        schema = Schema.build(
            name="t",
            dimensions=[("city", DataType.STRING)],
            metrics=[("v", DataType.LONG), ("price", DataType.DOUBLE)],
        )
        rng = np.random.default_rng(5)
        n = 200_000
        cols = {
            "city": np.array([f"c{j}" for j in rng.integers(0, 30, n)]),
            "v": rng.integers(0, 50, n).astype(np.int64),
            "price": np.round(rng.uniform(0, 100, n), 1),
        }
        dp, dz = str(tmp_path / "plain"), str(tmp_path / "zip")
        build_segment(schema, cols, dp, TableConfig(table_name="t"), "plain")
        build_segment(schema, cols, dz, TableConfig(
            table_name="t",
            indexing=IndexingConfig(compressed_columns=["v", "price"])), "zip")
        plain, comp = ImmutableSegment(dp), ImmutableSegment(dz)
        assert comp.column_metadata("v").compression == "zlib"
        assert comp.column_metadata("city").compression is None
        assert os.path.getsize(os.path.join(dz, "v.fwdz.bin")) \
            < os.path.getsize(os.path.join(dp, "v.fwd.npy")) / 3
        assert not os.path.exists(os.path.join(dz, "v.fwd.npy"))
        np.testing.assert_array_equal(
            np.asarray(comp.forward("v")), np.asarray(plain.forward("v")))

        ep, ez = QueryEngine(device_executor=None), QueryEngine(device_executor=None)
        ep.add_segment("t", plain)
        ez.add_segment("t", comp)
        for sql in (
            "SELECT COUNT(*), SUM(v), SUM(price) FROM t",
            "SELECT city, AVG(price) FROM t WHERE v > 25 "
            "GROUP BY city ORDER BY city LIMIT 10",
            "SELECT MAX(price), MIN(v) FROM t WHERE city = 'c3'",
        ):
            rp, rz = ep.execute(sql), ez.execute(sql)
            assert not rp.get("exceptions") and not rz.get("exceptions")
            assert rp["resultTable"]["rows"] == rz["resultTable"]["rows"], sql

    def test_row_value_on_compressed_column(self, tmp_path):
        schema = Schema.build(name="t", dimensions=[("k", DataType.STRING)],
                              metrics=[("v", DataType.LONG)])
        cols = {"k": np.array(["a", "b"]), "v": np.array([7, 9], dtype=np.int64)}
        d = str(tmp_path / "s")
        build_segment(schema, cols, d, TableConfig(
            table_name="t",
            indexing=IndexingConfig(compressed_columns=["v"])), "s0")
        seg = ImmutableSegment(d)
        assert seg.row_value("v", 1) == 9


class TestNumpyFallback:
    """Packed segments must stay readable with NO native library at all —
    the pure-numpy codec serves the same byte format (ISSUE 5 satellite:
    a host without g++/the .so must still load <col>.fwdpacked.bin)."""

    def _packed_segment(self, tmp_path):
        schema = Schema.build(
            name="t", dimensions=[("city", DataType.STRING)],
            metrics=[("v", DataType.LONG)])
        cfg = TableConfig(
            table_name="t",
            indexing=IndexingConfig(enable_bit_packing=True))
        rng = np.random.default_rng(9)
        n = 9000
        cols = {
            "city": np.array([f"c{j}" for j in range(23)])[
                rng.integers(0, 23, n)],
            "v": rng.integers(0, 1000, n).astype(np.int64),
        }
        d = str(tmp_path / "pk")
        build_segment(schema, cols, d, cfg, "s0")
        return d, cols

    def test_env_gate_forces_numpy(self, tmp_path, monkeypatch):
        d, cols = self._packed_segment(tmp_path)
        want = np.asarray(ImmutableSegment(d).forward("city"))
        monkeypatch.setenv("PINOT_TPU_NO_NATIVE", "1")
        assert not native.native_available()
        got = np.asarray(ImmutableSegment(d).forward("city"))
        np.testing.assert_array_equal(got, want)
        eng = QueryEngine(device_executor=None)
        eng.add_segment("t", ImmutableSegment(d))
        r = eng.execute("SELECT city, COUNT(*) FROM t GROUP BY city "
                        "ORDER BY city LIMIT 3")
        assert not r.get("exceptions"), r

    def test_unloadable_library_falls_back(self, tmp_path, monkeypatch):
        """A present-but-corrupt .so (or any load failure) must degrade to
        the numpy codec, not make packed segments unreadable."""
        d, cols = self._packed_segment(tmp_path)
        want = np.asarray(ImmutableSegment(d).forward("city"))
        # simulate: load already attempted and failed -> cached None
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_lib_tried", True)
        assert not native.native_available()
        got = np.asarray(ImmutableSegment(d).forward("city"))
        np.testing.assert_array_equal(got, want)
        # packed_size stays pure-python (the truncation guard's basis)
        assert native.packed_size(9000, 5) == (9000 * 5 + 7) // 8
