"""Peer segment download when the deep store is unreachable.

Reference: PeerServerSegmentFinder
(pinot-core/.../util/PeerServerSegmentFinder.java:1) +
PeerDownloadLLCRealtimeClusterIntegrationTest (deep-store-less commit).
"""

import os
import shutil
import time

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.cluster.registry import ClusterRegistry, SegmentState
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import StreamConfig, TableConfig, TableType
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.stream.memory_stream import TopicRegistry


def wait_until(cond, timeout=12.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


def test_offline_download_falls_back_to_peer(tmp_path):
    """A replica whose deep-store copy vanished loads the segment from the
    serving peer over the FetchSegment data plane."""
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "ds"))
    a = ServerInstance("srv_a", registry, str(tmp_path / "a"),
                       device_executor=None)
    a.start()
    broker = Broker(registry, timeout_s=10.0)
    b = None
    try:
        schema = Schema.build(name="ev", dimensions=[("k", DataType.STRING)],
                              metrics=[("v", DataType.INT)])
        cfg = TableConfig(table_name="ev", replication=2)
        controller.add_table(cfg, schema)
        rng = np.random.default_rng(2)
        cols = {"k": np.array(["x", "y"])[rng.integers(0, 2, 5000)],
                "v": rng.integers(0, 9, 5000).astype(np.int32)}
        d = str(tmp_path / "up")
        build_segment(schema, cols, d, cfg, "ev_s0")
        controller.upload_segment("ev", d)
        assert wait_until(
            lambda: "ev_s0" in a.engine.tables.get("ev_OFFLINE",
                                                   _Empty()).segments)

        # the deep store burns down AFTER server A loaded its copy
        rec = registry.segments("ev_OFFLINE")["ev_s0"]
        shutil.rmtree(rec.location)
        assert not os.path.isdir(rec.location)

        # a second replica joins: its deep-store copy MUST fail, and the
        # peer path must serve the segment from A
        b = ServerInstance("srv_b", registry, str(tmp_path / "b"),
                           device_executor=None)
        b.start()
        controller.rebalance("ev")
        assert wait_until(
            lambda: "ev_s0" in b.engine.tables.get("ev_OFFLINE",
                                                   _Empty()).segments,
            timeout=15), registry.assignment("ev_OFFLINE")

        # stop A: the peer-downloaded copy on B answers alone
        a.stop()
        assert wait_until(lambda: _count(broker) == 5000, timeout=10), \
            _count(broker)
    finally:
        broker.close()
        for s in (a, b):
            if s is not None:
                try:
                    s.stop()
                except Exception:
                    pass


class _Empty:
    segments: dict = {}


def _count(broker):
    r = broker.execute("SELECT COUNT(*) FROM ev")
    return -1 if r.get("exceptions") else r["resultTable"]["rows"][0][0]


def test_realtime_adopt_falls_back_to_peer(tmp_path, monkeypatch):
    """The commit-loser replica adopts via peer download when the winner's
    published location is unreachable (deep store down mid-commit)."""
    import pinot_tpu.realtime.completion as completion_mod

    TopicRegistry.delete("pd_clicks")
    topic = TopicRegistry.create("pd_clicks", 1)
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "ds"))
    servers = [ServerInstance(f"s{i}", registry, str(tmp_path / f"srv{i}"),
                              device_executor=None) for i in range(2)]
    for s in servers:
        s.start()
    broker = Broker(registry, timeout_s=10.0)

    # deep store down: every direct copy of a committed segment dir fails,
    # so the loser MUST ride the peer data plane
    def broken_adopt(entry, dest_dir):
        raise OSError("deep store unreachable (fault injection)")

    monkeypatch.setattr(completion_mod, "adopt_segment", broken_adopt)
    try:
        schema = Schema.build(name="pd_clicks",
                              dimensions=[("page", DataType.STRING)],
                              metrics=[("n", DataType.INT)])
        cfg = TableConfig(
            table_name="pd_clicks", table_type=TableType.REALTIME,
            replication=2,
            stream=StreamConfig(
                stream_type="memory", topic="pd_clicks", decoder="json",
                segment_flush_threshold_rows=60,
                segment_flush_threshold_seconds=3600,
            ),
        )
        controller.add_table(cfg, schema)

        def adoption_counts():
            total = 0
            for s in servers:
                mgr = s._realtime_managers.get("pd_clicks_REALTIME")
                if mgr:
                    total += sum(pm.adoptions
                                 for pm in mgr.partition_managers.values())
            return total

        def count():
            r = broker.execute("SELECT COUNT(*) FROM pd_clicks")
            return -1 if r.get("exceptions") else r["resultTable"]["rows"][0][0]

        # two waves → two commit rounds; each round's loser can only adopt
        # through the peer data plane (direct adopt is fault-injected)
        for wave in (1, 2):
            for i in range(150):
                topic.publish_json({"page": f"p{i % 3}", "n": 1}, partition=0)
            assert wait_until(lambda: adoption_counts() >= wave, timeout=20), \
                (wave, adoption_counts())
            assert wait_until(lambda: count() == 150 * wave, timeout=10), \
                (wave, count())
        assert any(rec.state == SegmentState.ONLINE
                   for rec in registry.segments("pd_clicks_REALTIME").values())
    finally:
        broker.close()
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
        TopicRegistry.delete("pd_clicks")


def test_extraction_tmpdir_removed_when_replace_fails(tmp_path, monkeypatch):
    """os.replace failing AFTER extractall used to leak the
    ``{dest_dir}.peer<pid>`` extraction dir; the per-replica try/finally
    must remove it on every exit path."""
    import io
    import tarfile
    import types

    from pinot_tpu.server import peer as peer_mod

    # a minimal tar payload holding <segment>/file
    seg_src = tmp_path / "src" / "seg1"
    seg_src.mkdir(parents=True)
    (seg_src / "cols.bin").write_bytes(b"payload")
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        tar.add(str(seg_src), arcname="seg1")
    tar_bytes = buf.getvalue()

    class FakeChannel:
        def __init__(self, addr, tls=None):
            pass

        def fetch_segment(self, req, timeout_s=None):
            yield tar_bytes

        def close(self):
            pass

    import pinot_tpu.transport.grpc_transport as gt

    monkeypatch.setattr(gt, "QueryRouterChannel", FakeChannel)

    real_replace = os.replace

    def broken_replace(src, dst):
        raise OSError("cross-device link (simulated)")

    monkeypatch.setattr(os, "replace", broken_replace)

    info = types.SimpleNamespace(instance_id="peer1", host="127.0.0.1",
                                 grpc_port=1234)
    registry = types.SimpleNamespace(
        external_view=lambda table: {"seg1": ["peer1", "me"]},
        instances=lambda: [info])

    dest = str(tmp_path / "tables" / "ev" / "seg1")
    with pytest.raises(RuntimeError, match="peer download"):
        peer_mod.peer_download(registry, "ev_OFFLINE", "seg1", dest, "me")
    leak = f"{dest}.peer{os.getpid()}"
    assert not os.path.isdir(leak), "extraction tmp dir leaked"
    monkeypatch.setattr(os, "replace", real_replace)
