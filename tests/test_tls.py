"""TLS on the data plane (gRPC) and broker HTTP.

Reference analogs: TlsConfig.java:1 + NettyConfig + the TLS cluster
integration tests — a TLS cluster serves queries end-to-end, and a
plaintext client is rejected.
"""

import json
import ssl
import time
import urllib.request

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.common.tls import TlsConfig, generate_self_signed
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment


@pytest.fixture(scope="module")
def tls(tmp_path_factory):
    return generate_self_signed(str(tmp_path_factory.mktemp("certs")))


@pytest.fixture()
def tls_cluster(tmp_path, tls):
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "ds"))
    server = ServerInstance("s0", registry, str(tmp_path / "srv"),
                            device_executor=None, tls=tls)
    server.start()
    broker = Broker(registry, tls=tls)
    schema = Schema.build(name="t", dimensions=[("k", DataType.STRING)],
                          metrics=[("v", DataType.INT)])
    cfg = TableConfig(table_name="t")
    controller.add_table(cfg, schema)
    d = str(tmp_path / "seg")
    build_segment(schema, {"k": np.array(["a", "b"] * 500),
                           "v": np.arange(1000, dtype=np.int32)}, d, cfg, "t_0")
    controller.upload_segment("t", d)
    yield registry, server, broker
    broker.close()
    server.stop()


def _query_until(broker, sql, timeout=10):
    deadline = time.time() + timeout
    r = None
    while time.time() < deadline:
        r = broker.execute(sql)
        if not r.get("exceptions"):
            return r
        time.sleep(0.1)
    raise AssertionError(r)


class TestGrpcTls:
    def test_tls_cluster_serves_queries(self, tls_cluster):
        registry, server, broker = tls_cluster
        assert server.transport.tls_enabled
        r = _query_until(broker, "SELECT COUNT(*), SUM(v) FROM t")
        assert r["resultTable"]["rows"][0] == [1000, float(sum(range(1000)))]

    def test_plaintext_client_rejected(self, tls_cluster):
        """A non-TLS channel to a TLS server must fail the handshake, not
        silently serve (the deployable-posture check)."""
        registry, server, broker = tls_cluster
        from pinot_tpu.transport.grpc_transport import (
            QueryRouterChannel,
            make_instance_request,
        )

        _query_until(broker, "SELECT COUNT(*) FROM t")  # server is up
        plain = QueryRouterChannel(server.transport.endpoint, tls=None)
        try:
            with pytest.raises(Exception):
                plain.submit(
                    make_instance_request("SELECT COUNT(*) FROM t", ["t_0"], 1),
                    timeout_s=3,
                )
        finally:
            plain.close()

    def test_wrong_ca_rejected(self, tls_cluster, tmp_path):
        registry, server, broker = tls_cluster
        from pinot_tpu.transport.grpc_transport import (
            QueryRouterChannel,
            make_instance_request,
        )

        other = generate_self_signed(str(tmp_path / "othercerts"))
        _query_until(broker, "SELECT COUNT(*) FROM t")
        bad = QueryRouterChannel(server.transport.endpoint, tls=other)
        try:
            with pytest.raises(Exception):
                bad.submit(
                    make_instance_request("SELECT COUNT(*) FROM t", ["t_0"], 1),
                    timeout_s=3,
                )
        finally:
            bad.close()


class TestHttpsTls:
    def test_https_query_and_plaintext_rejected(self, tls_cluster, tls):
        registry, server, broker = tls_cluster
        from pinot_tpu.broker.http_api import BrokerHttpServer

        _query_until(broker, "SELECT COUNT(*) FROM t")
        srv = BrokerHttpServer(broker, tls=tls)
        srv.start()
        try:
            assert srv.url.startswith("https://")
            ctx = tls.client_ssl_context()
            ctx.check_hostname = False  # cert CN=localhost, dialing by IP
            req = urllib.request.Request(
                srv.url + "/query/sql",
                data=json.dumps({"sql": "SELECT COUNT(*) FROM t"}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=5, context=ctx) as resp:
                out = json.loads(resp.read())
            assert out["resultTable"]["rows"][0][0] == 1000

            # plain http to the TLS port fails
            with pytest.raises(Exception):
                urllib.request.urlopen(
                    f"http://{srv.host}:{srv.port}/health", timeout=3)

            # an https client that doesn't trust the CA fails verification
            with pytest.raises(Exception):
                urllib.request.urlopen(
                    srv.url + "/health", timeout=3,
                    context=ssl.create_default_context())
        finally:
            srv.stop()

    def test_dbapi_client_over_https(self, tls_cluster, tls):
        registry, server, broker = tls_cluster
        from pinot_tpu.broker.http_api import BrokerHttpServer
        from pinot_tpu.client import connect

        _query_until(broker, "SELECT COUNT(*) FROM t")
        srv = BrokerHttpServer(broker, tls=tls)
        srv.start()
        try:
            ctx = tls.client_ssl_context()
            ctx.check_hostname = False
            conn = connect(srv.url, ssl_context=ctx)
            cur = conn.cursor()
            cur.execute("SELECT k, COUNT(*) FROM t GROUP BY k ORDER BY k")
            rows = cur.fetchall()
            assert rows == [("a", 500), ("b", 500)]
            conn.close()
        finally:
            srv.stop()


class TestTlsConfigLoading:
    def test_from_config_disabled_by_default(self):
        assert TlsConfig.from_config() is None

    def test_from_config_enabled(self, tls):
        from pinot_tpu.common.config import Configuration

        cfg = Configuration(overrides={
            "pinot.tls.enabled": "true",
            "pinot.tls.cert_file": tls.cert_file,
            "pinot.tls.key_file": tls.key_file,
            "pinot.tls.target_name_override": "localhost",
        })
        t = TlsConfig.from_config(cfg)
        assert t is not None and t.cert_file == tls.cert_file
        assert t.channel_options() == [
            ("grpc.ssl_target_name_override", "localhost")]

    def test_missing_files_raise(self):
        from pinot_tpu.common.config import Configuration

        cfg = Configuration(overrides={"pinot.tls.enabled": "true"})
        with pytest.raises(ValueError):
            TlsConfig.from_config(cfg)
