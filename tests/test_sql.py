"""SQL parser / compiler / optimizer tests (reference analog:
pinot-common CalciteSqlCompilerTest + pinot-core QueryOptimizer tests)."""

import pytest

from pinot_tpu.query.context import (
    Expression,
    FilterNode,
    FilterNodeType,
    PredicateType,
)
from pinot_tpu.query.optimizer import optimize_filter, optimize_query
from pinot_tpu.sql.compiler import compile_query
from pinot_tpu.sql.parser import SqlParseError, parse_sql


class TestParser:
    def test_basic_select(self):
        q = compile_query("SELECT a, b FROM t")
        assert q.table_name == "t"
        assert [str(e) for e in q.select_expressions] == ["a", "b"]
        assert q.limit == 10  # default

    def test_star_and_count_star(self):
        q = compile_query("SELECT COUNT(*) FROM t")
        e = q.select_expressions[0]
        assert e.is_function and e.name == "count"
        q2 = compile_query("SELECT * FROM t LIMIT 5")
        assert q2.select_expressions[0].name == "*"
        assert q2.limit == 5

    def test_aliases_and_group_order(self):
        q = compile_query(
            "SELECT playerName AS p, SUM(runs) AS total FROM baseballStats "
            "GROUP BY p ORDER BY total DESC LIMIT 3"
        )
        assert q.aliases == ("p", "total")
        assert str(q.group_by[0]) == "playerName"
        ob = q.order_by[0]
        assert not ob.ascending and str(ob.expression) == "sum(runs)"

    def test_ordinal_group_by(self):
        q = compile_query("SELECT league, COUNT(*) FROM t GROUP BY 1")
        assert str(q.group_by[0]) == "league"

    def test_where_tree(self):
        q = compile_query(
            "SELECT a FROM t WHERE x = 3 AND (y > 1.5 OR name IN ('a','b')) AND NOT z = 'q'"
        )
        f = q.filter
        assert f.type is FilterNodeType.AND

    def test_between_like_null(self):
        q = compile_query(
            "SELECT a FROM t WHERE x BETWEEN 2 AND 9 AND name LIKE 'foo%' AND b IS NOT NULL"
        )
        preds = [c.predicate for c in q.filter.children]
        assert preds[0].type is PredicateType.RANGE
        assert preds[0].lower == 2 and preds[0].upper == 9
        assert preds[1].type is PredicateType.LIKE
        assert preds[2].type is PredicateType.IS_NOT_NULL

    def test_not_in(self):
        q = compile_query("SELECT a FROM t WHERE x NOT IN (1, 2, 3)")
        p = q.filter.predicate
        assert p.type is PredicateType.NOT_IN and p.values == (1, 2, 3)

    def test_flipped_comparison(self):
        q = compile_query("SELECT a FROM t WHERE 5 < x")
        p = q.filter.predicate
        assert p.type is PredicateType.RANGE
        assert p.lower == 5 and not p.lower_inclusive and p.upper is None

    def test_limit_offset_forms(self):
        q = compile_query("SELECT a FROM t LIMIT 20 OFFSET 40")
        assert q.limit == 20 and q.offset == 40
        q2 = compile_query("SELECT a FROM t LIMIT 40, 20")
        assert q2.limit == 20 and q2.offset == 40

    def test_set_options_and_explain(self):
        q = compile_query("SET timeoutMs = 500; SET useStarTree = false; "
                          "EXPLAIN PLAN FOR SELECT a FROM t")
        assert q.explain
        assert q.options_dict() == {"timeoutMs": 500, "useStarTree": False}

    def test_expression_arith(self):
        q = compile_query("SELECT a + b * 2, SUM(c) / COUNT(*) FROM t")
        e = q.select_expressions[0]
        assert e.name == "plus"
        assert e.args[1].name == "times"

    def test_count_distinct(self):
        q = compile_query("SELECT COUNT(DISTINCT a) FROM t")
        assert q.select_expressions[0].name == "distinctcount"

    def test_case_when(self):
        q = compile_query("SELECT CASE WHEN a > 1 THEN 'x' ELSE 'y' END FROM t")
        e = q.select_expressions[0]
        assert e.name == "case" and len(e.args) == 3

    def test_cast(self):
        q = compile_query("SELECT CAST(a AS LONG) FROM t")
        e = q.select_expressions[0]
        assert e.name == "cast" and e.args[1].value == "LONG"

    def test_quoted_identifiers_and_string_escape(self):
        q = compile_query('SELECT "select" FROM t WHERE s = \'it''s\'')
        assert q.select_expressions[0].name == "select"

    def test_errors(self):
        with pytest.raises(SqlParseError):
            parse_sql("SELECT FROM t")
        with pytest.raises(SqlParseError):
            parse_sql("SELECT a FROM t WHERE")
        with pytest.raises(SqlParseError):
            parse_sql("SELECT a FROM t trailing garbage ,")

    def test_aggregations_listing(self):
        q = compile_query(
            "SELECT league, SUM(runs), MAX(hits) FROM t GROUP BY league "
            "HAVING SUM(runs) > 10 ORDER BY MIN(salary)"
        )
        aggs = [str(a) for a in q.aggregations()]
        assert aggs == ["sum(runs)", "max(hits)", "min(salary)"]


class TestOptimizer:
    def test_flatten_and_merge_in(self):
        q = compile_query("SELECT a FROM t WHERE x = 1 OR x = 2 OR x IN (2, 3)")
        f = optimize_filter(q.filter)
        assert f.type is FilterNodeType.PREDICATE
        assert f.predicate.type is PredicateType.IN
        assert set(f.predicate.values) == {1, 2, 3}

    def test_merge_ranges(self):
        q = compile_query("SELECT a FROM t WHERE x > 3 AND x <= 10 AND x >= 4")
        f = optimize_filter(q.filter)
        p = f.predicate
        assert p.lower == 4 and p.lower_inclusive
        assert p.upper == 10 and p.upper_inclusive

    def test_empty_range_folds_false(self):
        q = compile_query("SELECT a FROM t WHERE x > 10 AND x < 5")
        f = optimize_filter(q.filter)
        assert f.type is FilterNodeType.CONSTANT_FALSE

    def test_and_intersect_eq(self):
        q = compile_query("SELECT a FROM t WHERE x = 1 AND x = 2")
        f = optimize_filter(q.filter)
        assert f.type is FilterNodeType.CONSTANT_FALSE

    def test_double_not(self):
        q = compile_query("SELECT a FROM t WHERE NOT NOT x = 1")
        f = optimize_filter(q.filter)
        assert f.type is FilterNodeType.PREDICATE

    def test_optimize_query_noop_without_filter(self):
        q = compile_query("SELECT a FROM t")
        assert optimize_query(q) is q
