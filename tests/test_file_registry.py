"""Sectioned FileRegistry: incremental persistence + cross-process
visibility (the ZK-state analog for multi-process clusters).

r2 verdict weak-point: the old FileRegistry rewrote/re-parsed the entire
JSON state per transaction. Now transactions touch only their sections,
and a per-section version stamp lets pollers reuse cached parses.
"""

import os
import threading

import pytest

from pinot_tpu.cluster.registry import (
    ClusterRegistry,
    FileRegistry,
    InstanceInfo,
    Role,
    SegmentRecord,
)
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig


def _schema():
    return Schema.build(name="t", dimensions=[("k", DataType.STRING)])


class TestSectionedPersistence:
    def test_sections_on_disk_and_cross_instance_visibility(self, tmp_path):
        path = str(tmp_path / "cluster.json")
        a = FileRegistry(path)
        a.register_instance(InstanceInfo("s1", Role.SERVER, grpc_port=1))
        a.add_table(TableConfig(table_name="t"), _schema(), key="t_OFFLINE")
        a.add_segment(SegmentRecord(name="seg0", table="t_OFFLINE"), ["s1"])
        assert os.path.isfile(os.path.join(path + ".d", "instances.json"))
        assert os.path.isfile(os.path.join(path + ".d", "segments.json"))

        b = FileRegistry(path)  # second process
        assert [i.instance_id for i in b.instances()] == ["s1"]
        assert list(b.segments("t_OFFLINE")) == ["seg0"]
        b.add_segment(SegmentRecord(name="seg1", table="t_OFFLINE"), ["s1"])
        # a sees b's write (version invalidation, no stale cache)
        assert sorted(a.segments("t_OFFLINE")) == ["seg0", "seg1"]

    def test_heartbeat_does_not_rewrite_segments(self, tmp_path):
        path = str(tmp_path / "c.json")
        reg = FileRegistry(path)
        reg.register_instance(InstanceInfo("s1", Role.SERVER))
        reg.add_table(TableConfig(table_name="t"), _schema(), key="t_OFFLINE")
        for i in range(50):
            reg.add_segment(
                SegmentRecord(name=f"seg{i}", table="t_OFFLINE"), ["s1"])
        seg_path = os.path.join(path + ".d", "segments.json")
        before = os.stat(seg_path).st_mtime_ns
        for _ in range(20):
            reg.heartbeat("s1")
        assert os.stat(seg_path).st_mtime_ns == before

    def test_idle_write_shaped_polls_do_not_churn(self, tmp_path):
        """claim_task on an empty queue / no-op txs must not rewrite files
        or bump versions (r3 review: 5 polls/sec would otherwise invalidate
        every peer's cache forever)."""
        path = str(tmp_path / "c.json")
        reg = FileRegistry(path)
        reg.add_table(TableConfig(table_name="t"), _schema(), key="t_OFFLINE")
        v0 = reg.state_version()
        tasks_path = os.path.join(path + ".d", "tasks.json")
        before = os.stat(tasks_path).st_mtime_ns
        for _ in range(10):
            assert reg.claim_task("minion_0") is None
        assert os.stat(tasks_path).st_mtime_ns == before
        assert reg.state_version() == v0

    def test_failed_write_back_does_not_poison_cache(self, tmp_path, monkeypatch):
        """A write-back crash (ENOSPC analog) must not leave this process
        serving uncommitted state its peers never saw (r3 review)."""
        path = str(tmp_path / "c.json")
        reg = FileRegistry(path)
        reg.register_instance(InstanceInfo("s1", Role.SERVER))

        real = FileRegistry._stage_section

        def boom(self, name, data):
            raise OSError("disk full")

        monkeypatch.setattr(FileRegistry, "_stage_section", boom)
        with pytest.raises(OSError):
            reg.register_instance(InstanceInfo("s2", Role.SERVER))
        monkeypatch.setattr(FileRegistry, "_stage_section", real)
        assert [i.instance_id for i in reg.instances()] == ["s1"]
        assert [i.instance_id for i in FileRegistry(path).instances()] == ["s1"]

    def test_partial_stage_failure_publishes_nothing(self, tmp_path, monkeypatch):
        """Cross-section tx atomicity (r3 advisor): if staging section B
        fails after section A staged OK, NEITHER section is published —
        peers must never observe a torn multi-section transaction."""
        path = str(tmp_path / "c.json")
        reg = FileRegistry(path)
        reg.register_instance(InstanceInfo("s1", Role.SERVER))

        real = FileRegistry._stage_section
        calls = {"n": 0}

        def fail_second(self, name, data):
            calls["n"] += 1
            if calls["n"] >= 2:
                raise OSError("disk full")
            return real(self, name, data)

        def multi_section_tx(state):
            # touch two sections so both are dirty in one tx
            state["instances"]["s2"] = state["instances"]["s1"]
            state["tasks"]["t1"] = {"status": "pending"}

        monkeypatch.setattr(FileRegistry, "_stage_section", fail_second)
        with pytest.raises(OSError):
            reg._tx(multi_section_tx)
        monkeypatch.setattr(FileRegistry, "_stage_section", real)
        # neither the staged-OK section nor the failed one is visible,
        # in this process or a fresh peer
        assert [i.instance_id for i in reg.instances()] == ["s1"]
        peer = FileRegistry(path)
        assert [i.instance_id for i in peer.instances()] == ["s1"]
        assert peer._tx(lambda s: dict(s["tasks"]), write=False) == {}
        # and no orphaned staging tmp files linger in the section dir
        assert not [f for f in os.listdir(reg.dir)
                    if f.split(".")[-1].isdigit()]

    def test_peer_crash_between_write_and_bump_not_stale(self, tmp_path):
        """Cache validates against the section FILE, not the version
        counter: a peer that died after os.replace but before the version
        bump must still be observed (r3 review)."""
        path = str(tmp_path / "c.json")
        a = FileRegistry(path)
        a.register_instance(InstanceInfo("s1", Role.SERVER))
        assert len(a.instances()) == 1  # warm a's cache

        b = FileRegistry(path)
        real_bump = FileRegistry._bump_version
        # b writes instances.json but "crashes" before bumping the version
        FileRegistry._bump_version = lambda self, sections=None: {}
        try:
            b.register_instance(InstanceInfo("s2", Role.SERVER))
        finally:
            FileRegistry._bump_version = real_bump
        assert {i.instance_id for i in a.instances()} == {"s1", "s2"}

    def test_legacy_single_file_migrates(self, tmp_path):
        import json

        path = str(tmp_path / "old.json")
        legacy = ClusterRegistry()
        legacy.register_instance(InstanceInfo("s9", Role.SERVER))
        legacy.add_table(TableConfig(table_name="t"), _schema(), key="t_OFFLINE")
        legacy.add_segment(SegmentRecord(name="seg0", table="t_OFFLINE"), ["s9"])
        from pinot_tpu.cluster.registry import _to_json

        with open(path, "w") as f:
            json.dump(_to_json(legacy._state), f)
        reg = FileRegistry(path)
        assert [i.instance_id for i in reg.instances()] == ["s9"]
        assert list(reg.segments("t_OFFLINE")) == ["seg0"]

    def test_failed_tx_poisons_nothing(self, tmp_path):
        reg = FileRegistry(str(tmp_path / "c.json"))
        reg.add_table(TableConfig(table_name="t"), _schema(), key="t_OFFLINE")

        def bad(s):
            s["tables"]["junk"] = {"oops": True}
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError):
            reg._tx(bad)
        assert reg.tables() == ["t_OFFLINE"]  # mutation not persisted/cached

    def test_state_version_advances_per_write(self, tmp_path):
        reg = FileRegistry(str(tmp_path / "c.json"))
        v0 = reg.state_version()
        reg.register_instance(InstanceInfo("x", Role.BROKER))
        v1 = reg.state_version()
        assert v1 > v0
        assert reg.state_version() == v1  # reads don't bump

    def test_concurrent_writers_consistent(self, tmp_path):
        path = str(tmp_path / "c.json")
        reg = FileRegistry(path)
        reg.add_table(TableConfig(table_name="t"), _schema(), key="t_OFFLINE")
        regs = [FileRegistry(path) for _ in range(4)]
        errs = []

        def writer(r, base):
            try:
                for i in range(25):
                    r.add_segment(SegmentRecord(
                        name=f"seg{base}_{i}", table="t_OFFLINE"), ["s1"])
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(r, j))
                   for j, r in enumerate(regs)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert len(reg.segments("t_OFFLINE")) == 100