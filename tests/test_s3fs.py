"""S3 PinotFS plugin against a faked boto3 (pinot-s3 analog).

No AWS SDK ships in this image, so a minimal in-memory fake provides the
client surface (upload/download/list/delete/copy) and the tests assert
the SPI mapping + the gating error without it.
"""

import sys
import types

import pytest

_STORE: dict = {}  # (bucket, key) -> bytes


class _FakeClient:
    def upload_file(self, filename, bucket, key):
        with open(filename, "rb") as f:
            _STORE[(bucket, key)] = f.read()

    def download_file(self, bucket, key, filename):
        with open(filename, "wb") as f:
            f.write(_STORE[(bucket, key)])

    def list_objects_v2(self, Bucket, Prefix, MaxKeys=None,
                        ContinuationToken=None):
        keys = sorted(k for (b, k) in _STORE
                      if b == Bucket and k.startswith(Prefix))
        if MaxKeys:
            keys = keys[:MaxKeys]
        return {"Contents": [{"Key": k} for k in keys], "IsTruncated": False}

    def delete_objects(self, Bucket, Delete):
        for obj in Delete["Objects"]:
            _STORE.pop((Bucket, obj["Key"]), None)

    def copy_object(self, Bucket, Key, CopySource):
        _STORE[(Bucket, Key)] = _STORE[
            (CopySource["Bucket"], CopySource["Key"])]


@pytest.fixture()
def fake_boto3(monkeypatch):
    mod = types.ModuleType("boto3")
    mod.client = lambda service, **kw: _FakeClient()
    monkeypatch.setitem(sys.modules, "boto3", mod)
    _STORE.clear()
    yield mod
    _STORE.clear()


class TestS3FS:
    def test_gating_error_without_boto3(self, monkeypatch):
        monkeypatch.setitem(sys.modules, "boto3", None)
        from pinot_tpu.storage.s3fs import S3FS

        with pytest.raises(RuntimeError, match="boto3"):
            S3FS()

    def test_scheme_registered(self, fake_boto3):
        from pinot_tpu.storage.fs import create_fs

        fs = create_fs("s3://bucket/deepstore")
        assert type(fs).__name__ == "S3FS"

    def test_segment_dir_roundtrip(self, fake_boto3, tmp_path):
        from pinot_tpu.storage.s3fs import S3FS

        src = tmp_path / "seg"
        (src / "sub").mkdir(parents=True)
        (src / "metadata.json").write_text("{}")
        (src / "col.fwd.npy").write_bytes(b"\x01\x02")
        (src / "sub" / "x.bin").write_bytes(b"\x03")

        fs = S3FS()
        fs.copy(str(src), "s3://b/tables/t/seg0")
        assert fs.exists("s3://b/tables/t/seg0")
        assert fs.list_files("s3://b/tables/t") == ["seg0"]

        dst = tmp_path / "download"
        fs.copy("s3://b/tables/t/seg0", str(dst))
        assert (dst / "metadata.json").read_text() == "{}"
        assert (dst / "col.fwd.npy").read_bytes() == b"\x01\x02"
        assert (dst / "sub" / "x.bin").read_bytes() == b"\x03"

        fs.delete("s3://b/tables/t/seg0")
        assert not fs.exists("s3://b/tables/t/seg0")

    def test_sibling_prefixes_are_isolated(self, fake_boto3, tmp_path):
        """seg_1 operations must never touch seg_10 (r3 review: raw
        prefix matching deleted same-prefix siblings)."""
        from pinot_tpu.storage.s3fs import S3FS

        a = tmp_path / "seg_1"
        b = tmp_path / "seg_10"
        a.mkdir(); b.mkdir()
        (a / "a.bin").write_bytes(b"A")
        (b / "b.bin").write_bytes(b"B")
        fs = S3FS()
        fs.copy(str(a), "s3://b/t/seg_1")
        fs.copy(str(b), "s3://b/t/seg_10")
        fs.delete("s3://b/t/seg_1")
        assert not fs.exists("s3://b/t/seg_1")
        assert fs.exists("s3://b/t/seg_10")
        d = tmp_path / "dl"
        fs.copy("s3://b/t/seg_10", str(d))
        assert (d / "b.bin").read_bytes() == b"B"

    def test_repush_replaces_stale_objects(self, fake_boto3, tmp_path):
        """Re-pushing a segment must REPLACE the destination (r3 review:
        stale objects from v1 survived under the prefix)."""
        from pinot_tpu.storage.s3fs import S3FS

        v1 = tmp_path / "v1"; v1.mkdir()
        (v1 / "a.bin").write_bytes(b"1")
        (v1 / "old.bin").write_bytes(b"1")
        v2 = tmp_path / "v2"; v2.mkdir()
        (v2 / "a.bin").write_bytes(b"2")
        fs = S3FS()
        fs.copy(str(v1), "s3://b/t/seg")
        fs.copy(str(v2), "s3://b/t/seg")
        d = tmp_path / "dl"
        fs.copy("s3://b/t/seg", str(d))
        assert (d / "a.bin").read_bytes() == b"2"
        assert not (d / "old.bin").exists()

    def test_missing_download_raises(self, fake_boto3, tmp_path):
        from pinot_tpu.storage.s3fs import S3FS

        with pytest.raises(FileNotFoundError):
            S3FS().copy("s3://b/nope", str(tmp_path / "d"))
