"""Zone-map pruning + device block-skip: differential parity + stats.

The contract under test (ISSUE 4): Level-1 launch-time segment skip (the
filter tree vs per-segment stats, alive-masked via the ``ps_alive`` param)
and Level-2 device block skip (per-block zone verdicts, static-bound
candidate compaction, gathered filter+aggregation) must answer EXACTLY like
the force-dense path (``SET useBlockSkip = false``) and the host executor,
across EQ/IN/RANGE/AND/OR/NOT on dict and raw columns, sealed + consuming
segments, solo and 8-dev mesh, and coalesced cohorts whose members prune
different segment subsets — while the scan stats get honest (entries
scanned counts only gathered rows, numBlocksPruned/numSegmentsPrunedByServer
surface the pruning).
"""

import threading

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import (
    ZONE_BLOCK_ROWS,
    ImmutableSegment,
    build_zone_map,
)

N_SEG = 3
ROWS = 20_000  # pad_to 20480 = 5 zone blocks per segment


def _make_cols(rng, n, seg_idx):
    """Time-ordered layout: ``ts`` ascends globally across segments and
    ``k`` is block-clustered (a new value every 5000 rows) — the shapes
    zone maps discriminate on. ``tag``/``m``/``f`` are unclustered."""
    base = seg_idx * n
    return {
        "ts": (base + np.arange(n)).astype(np.int64),
        "k": np.array([f"k{(base + i) // 5000:04d}" for i in range(n)]),
        "tag": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
        "m": rng.integers(0, 10_000, n).astype(np.int32),
        "f": np.round(rng.uniform(0, 100, n), 3),
    }


@pytest.fixture(scope="module")
def tables(tmp_path_factory):
    rng = np.random.default_rng(29)
    schema = Schema.build(
        name="t",
        dimensions=[("ts", DataType.LONG), ("k", DataType.STRING),
                    ("tag", DataType.STRING)],
        metrics=[("m", DataType.INT), ("f", DataType.DOUBLE)],
    )
    cfg = TableConfig(
        table_name="t",
        indexing=IndexingConfig(no_dictionary_columns=["ts"]),
    )
    base = tmp_path_factory.mktemp("bskip")
    segs, all_cols = [], []
    for i in range(N_SEG):
        cols = _make_cols(rng, ROWS, i)
        all_cols.append(cols)
        build_segment(schema, cols, str(base / f"s{i}"), cfg, f"s{i}")
        segs.append(ImmutableSegment(str(base / f"s{i}")))
    return segs, all_cols


def _engine(segs, device="auto"):
    eng = QueryEngine() if device == "auto" \
        else QueryEngine(device_executor=device)
    for s in segs:
        eng.add_segment("t", s)
    return eng


@pytest.fixture(scope="module")
def engines(tables):
    segs, all_cols = tables
    return _engine(segs), _engine(segs, device=None), all_cols


# EQ / IN / RANGE / AND / OR / NOT over dict (k, tag) and raw (ts, m)
# columns; scalar and group-by shapes; selective, empty, and unselective.
PARITY_QUERIES = [
    "SELECT COUNT(*), SUM(m) FROM t WHERE ts BETWEEN 5000 AND 5999",
    "SELECT COUNT(*), SUM(m), MIN(m), MAX(m) FROM t WHERE ts < 3000",
    "SELECT COUNT(*) FROM t WHERE k = 'k0002'",
    "SELECT COUNT(*), SUM(f) FROM t WHERE k IN ('k0001', 'k0009')",
    "SELECT tag, COUNT(*), SUM(m) FROM t WHERE ts BETWEEN 10000 AND 30000 "
    "GROUP BY tag ORDER BY tag",
    "SELECT COUNT(*) FROM t WHERE ts > 15000 AND k = 'k0004'",
    "SELECT COUNT(*) FROM t WHERE ts < 2000 OR ts > 55000",
    "SELECT COUNT(*) FROM t WHERE NOT ts < 30000",
    "SELECT COUNT(*) FROM t WHERE tag = 'b' AND ts BETWEEN 4096 AND 8191",
    "SELECT k, COUNT(*) FROM t WHERE ts BETWEEN 4000 AND 21000 "
    "GROUP BY k ORDER BY k",
    # empty but not segment-prunable (each conjunct alone may match):
    # exercises the all-false kernel paths on both forms
    "SELECT COUNT(*), MIN(m), MAX(m) FROM t WHERE ts = 5000 AND ts = 9000",
    # provably false everywhere (absent dictionary value): the launch is
    # SKIPPED and neutral partials synthesized
    "SELECT COUNT(*), MIN(m), MAX(m) FROM t WHERE k = 'zzz'",
    # unselective: candidate count overflows the static bound, the
    # in-kernel dense fallback engages
    "SELECT COUNT(*), SUM(m) FROM t WHERE ts >= 0",
]


def _close(a, b):
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, str) or isinstance(b, str):
        return a == b
    return np.isclose(float(a), float(b), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("sql", PARITY_QUERIES)
def test_pruned_equals_dense_equals_host(engines, sql):
    dev, host, _ = engines
    r_skip = dev.execute(sql)
    r_dense = dev.execute("SET useBlockSkip = false; " + sql)
    r_host = host.execute(sql)
    assert not r_skip.get("exceptions"), r_skip
    assert not r_dense.get("exceptions"), r_dense
    # pruned vs force-dense: EXACT (same kernels, same dtypes, pruning
    # only removes provably-non-matching work)
    assert r_skip["resultTable"] == r_dense["resultTable"], sql
    assert r_skip["numDocsScanned"] == r_dense["numDocsScanned"]
    assert r_skip["totalDocs"] == r_dense["totalDocs"]
    # vs host: value-equal (device float columns are f32-narrowed)
    rows_s, rows_h = r_skip["resultTable"]["rows"], r_host["resultTable"]["rows"]
    assert len(rows_s) == len(rows_h), sql
    for rs, rh in zip(rows_s, rows_h):
        assert all(_close(a, b) for a, b in zip(rs, rh)), (sql, rs, rh)


class TestStats:
    def test_selective_range_prunes_blocks(self, engines):
        dev, _, _ = engines
        sql = "SELECT COUNT(*), SUM(m) FROM t WHERE ts BETWEEN 5000 AND 5999"
        r = dev.execute(sql)
        rd = dev.execute("SET useBlockSkip = false; " + sql)
        assert r["numBlocksPruned"] > 0
        assert rd["numBlocksPruned"] == 0
        # honest scan accounting: only gathered blocks' rows counted
        assert 0 < r["numEntriesScannedInFilter"] \
            < rd["numEntriesScannedInFilter"]
        # Level 1 also fires: the window lives entirely in segment 0
        assert r["numSegmentsPrunedByServer"] == N_SEG - 1
        assert rd["numSegmentsPrunedByServer"] == N_SEG - 1
        assert r["numSegmentsProcessed"] == 1

    def test_fully_pruned_skips_launch(self, engines):
        dev, _, _ = engines
        r = dev.execute("SELECT COUNT(*) FROM t WHERE k = 'zzz'")
        assert r["resultTable"]["rows"][0][0] == 0
        assert r["numSegmentsPrunedByServer"] == N_SEG
        assert r["numDocsScanned"] == 0
        assert r["numEntriesScannedInFilter"] == 0
        # pruned segments still count toward totalDocs
        assert r["totalDocs"] == N_SEG * ROWS

    def test_overflow_falls_back_dense(self, engines):
        dev, _, all_cols = engines
        # matches every block: candidates > the static bound -> dense
        r = dev.execute("SELECT COUNT(*) FROM t WHERE ts >= 0")
        assert r["numBlocksPruned"] == 0
        assert r["resultTable"]["rows"][0][0] == N_SEG * ROWS

    def test_candidate_bound_boundary(self, tables):
        """Sweep window sizes across the static candidate bound: every
        width must stay parity-exact whether the skip or the overflow
        (dense) branch runs."""
        segs, all_cols = tables
        dev = _engine(segs)
        ts = np.concatenate([c["ts"] for c in all_cols])
        m = np.concatenate([c["m"] for c in all_cols])
        # total blocks = 15, bound = ceil(15/16) = 1: windows spanning
        # 1, 2, and 8 blocks cross the bound in both directions
        for width in (ZONE_BLOCK_ROWS // 2, ZONE_BLOCK_ROWS,
                      2 * ZONE_BLOCK_ROWS, 8 * ZONE_BLOCK_ROWS):
            lo, hi = 1000, 1000 + width - 1
            r = dev.execute(
                f"SELECT COUNT(*), SUM(m) FROM t "
                f"WHERE ts BETWEEN {lo} AND {hi}")
            want = (ts >= lo) & (ts <= hi)
            assert r["resultTable"]["rows"][0][0] == int(want.sum()), width
            assert int(float(r["resultTable"]["rows"][0][1])) == \
                int(m[want].sum()), width


class TestMesh:
    @pytest.mark.parametrize("sql", PARITY_QUERIES)
    def test_mesh_parity(self, tables, sql):
        from pinot_tpu.engine.device import DeviceExecutor
        from pinot_tpu.parallel.mesh import make_mesh

        segs, _ = tables
        mesh_eng = _engine(segs, DeviceExecutor(mesh=make_mesh(8)))
        host_eng = _engine(segs, None)
        rm = mesh_eng.execute(sql)
        rh = host_eng.execute(sql)
        assert not rm.get("exceptions"), rm
        rows_m, rows_h = rm["resultTable"]["rows"], rh["resultTable"]["rows"]
        assert len(rows_m) == len(rows_h), sql
        for a, b in zip(rows_m, rows_h):
            assert all(_close(x, y) for x, y in zip(a, b)), (sql, a, b)


class TestCohorts:
    def test_cohort_members_prune_different_segments(self, tables):
        """Coalesced cohort whose members' literals prune DIFFERENT
        segment subsets: ps_alive is a per-member param inside the vmapped
        launch, so every member must still answer exactly like its solo
        run."""
        segs, all_cols = tables
        eng = _engine(segs)
        # one window per segment + one spanning two: same template,
        # different alive vectors
        windows = [(100, 1500), (21000, 22000), (45000, 46000),
                   (19000, 41000)]
        sqls = [f"SELECT COUNT(*), SUM(m) FROM t "
                f"WHERE ts BETWEEN {lo} AND {hi}" for lo, hi in windows]
        expected = [eng.execute(s) for s in sqls]  # solo (warm + oracle)
        # the warm pass populated the device partials cache; this test
        # exercises the COHORT machinery, so keep repeats off the cache
        eng.device.partials_cache_enabled = False
        co = eng.device.coalescer
        co.force = True
        co.window_s = 0.05
        c0 = co.queries_coalesced
        try:
            barrier = threading.Barrier(len(sqls))
            got = [None] * len(sqls)
            errs = []

            def worker(i):
                try:
                    barrier.wait()
                    got[i] = eng.execute(sqls[i])
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)

            ts = [threading.Thread(target=worker, args=(i,))
                  for i in range(len(sqls))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        finally:
            co.force = False
        assert not errs, errs
        for i, (g, e) in enumerate(zip(got, expected)):
            assert g["resultTable"] == e["resultTable"], sqls[i]
            assert g["numDocsScanned"] == e["numDocsScanned"], sqls[i]
        assert co.queries_coalesced > c0, "no query joined a cohort"


class TestConsumingSegments:
    def test_chunklet_batch_prunes(self, tmp_path):
        """Consuming segments prune too: promoted chunklets carry their
        own zone maps (refreshed per promotion), ride the chunklet device
        batch, and a selective ts range skips their blocks — answers
        staying identical to the all-host scan."""
        from pinot_tpu.common.table_config import ChunkletConfig
        from pinot_tpu.storage.mutable import MutableSegment

        schema = Schema.build(
            name="rt",
            dimensions=[("ts", DataType.LONG), ("tag", DataType.STRING)],
            metrics=[("m", DataType.INT)],
        )
        cfg = TableConfig(
            table_name="rt",
            indexing=IndexingConfig(no_dictionary_columns=["ts"]),
            chunklets=ChunkletConfig(enabled=True, rows_per_chunklet=8192,
                                     device_min_rows=8192),
        )
        rng = np.random.default_rng(41)
        n = 40_000
        tags = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
        ms = rng.integers(0, 1000, n)
        rows = [{"ts": int(i), "tag": str(t), "m": int(v)}
                for i, (t, v) in enumerate(zip(tags, ms))]
        seg = MutableSegment(schema, "rt__0__0__0", cfg)
        for i in range(0, n, 8192):
            seg.index_batch(rows[i:i + 8192])
            seg.chunklet_index.promote()
        assert seg.chunklet_index.chunklets, "no chunklets promoted"

        dev = QueryEngine()
        dev.add_segment("rt", seg)
        host = QueryEngine(device_executor=None)
        host.add_segment("rt", seg)
        for sql in (
            "SELECT COUNT(*), SUM(m) FROM rt WHERE ts BETWEEN 3000 AND 3999",
            "SELECT tag, COUNT(*) FROM rt WHERE ts < 2500 "
            "GROUP BY tag ORDER BY tag",
            "SELECT COUNT(*) FROM rt WHERE ts BETWEEN 8192 AND 12287 "
            "AND tag = 'b'",
        ):
            rd, rh = dev.execute(sql), host.execute(sql)
            assert not rd.get("exceptions"), rd
            assert rd["resultTable"]["rows"] == rh["resultTable"]["rows"], sql
        r = dev.execute(
            "SELECT COUNT(*) FROM rt WHERE ts BETWEEN 3000 AND 3999")
        assert r["numBlocksPruned"] > 0  # chunklet zone maps engaged


class TestKernelNeutralFills:
    def test_neutral_outs_match_all_masked_kernel(self):
        """The fully-pruned synthesized outputs must equal what the dense
        kernel produces with every segment alive-masked — bit-for-bit, so
        full-prune skip vs force-dense parity holds for every agg fill."""
        import jax
        import jax.numpy as jnp

        from pinot_tpu.engine.device import (
            _neutral_outs,
            _out_layout,
            build_pipeline,
        )

        template = (
            "agg",
            ("eq_raw", ("raw", "v"), "pr0"),
            (), (),
            (("count", None, None),
             ("sum", ("raw", "v"), (None, None)),
             ("min", ("raw", "v"), None),
             ("max", ("raw", "v"), None)),
            0, False,
        )
        fn = build_pipeline(template, mm_mode="off")
        cols = {"v": jnp.asarray(
            np.arange(2 * ZONE_BLOCK_ROWS, dtype=np.int32).reshape(2, -1))}
        n_docs = jnp.asarray(np.array([4000, 3000], dtype=np.int32))
        params = {"pr0": jnp.asarray(np.int32(7)),
                  "ps_alive": jnp.zeros(2, dtype=bool)}
        outs = {k: np.asarray(v)
                for k, v in jax.jit(fn)(cols, n_docs, params).items()}
        layout = _out_layout(jax.eval_shape(fn, cols, n_docs, params))
        synth = _neutral_outs(layout)
        assert set(outs) == set(synth)
        for k in outs:
            assert np.array_equal(outs[k].astype(synth[k].dtype),
                                  synth[k]), k


class TestZoneMapFormat:
    def test_creator_persists_zone_maps(self, tables):
        segs, all_cols = tables
        zm = segs[0].zone_map("m")
        assert zm is not None
        want = build_zone_map(np.asarray(segs[0].forward("m")))
        np.testing.assert_array_equal(np.asarray(zm), want)
        # dict column: local-id space
        zmk = segs[0].zone_map("k")
        fwd = np.asarray(segs[0].forward("k"))
        np.testing.assert_array_equal(
            np.asarray(zmk), build_zone_map(fwd))

    def test_missing_zone_map_recomputes(self, tmp_path):
        """Pre-zone-map segments (no .zmap.npy) still prune: the batch
        loader recomputes from the column block."""
        import os

        schema = Schema.build(
            name="t2", dimensions=[("ts", DataType.LONG)],
            metrics=[("m", DataType.INT)])
        cfg = TableConfig(
            table_name="t2",
            indexing=IndexingConfig(no_dictionary_columns=["ts"]))
        n = 10_000
        cols = {"ts": np.arange(n, dtype=np.int64),
                "m": np.arange(n, dtype=np.int32) % 97}
        build_segment(schema, cols, str(tmp_path / "s0"), cfg, "s0")
        for f in os.listdir(tmp_path / "s0"):
            if f.endswith(".zmap.npy"):
                os.unlink(tmp_path / "s0" / f)
        seg = ImmutableSegment(str(tmp_path / "s0"))
        assert seg.zone_map("ts") is None
        eng = QueryEngine()
        eng.add_segment("t2", seg)
        sql = "SELECT COUNT(*) FROM t2 WHERE ts BETWEEN 100 AND 199"
        r = eng.execute(sql)
        assert r["resultTable"]["rows"][0][0] == 100
        assert r["numBlocksPruned"] > 0


class TestHostBloomShortCircuit:
    def test_bloom_short_circuits_before_decode(self, baseball_segment):
        """EQ/IN on a bloom-indexed column proves a segment empty before
        the forward index is read — numEntriesScannedInFilter stays 0 even
        under an OR (which the segment-level pruner cannot touch)."""
        from pinot_tpu.engine.host import SegmentEvaluator
        from pinot_tpu.query.context import (
            Expression,
            Predicate,
            PredicateType,
        )

        ev = SegmentEvaluator(baseball_segment)
        p = Predicate(PredicateType.EQ,
                      Expression.identifier("playerName"),
                      value="nonexistent_player")
        mask = ev.predicate_mask(p)
        assert not mask.any()
        assert ev.entries_scanned_in_filter == 0
        p_in = Predicate(PredicateType.IN,
                         Expression.identifier("playerName"),
                         values=("ghost_1", "ghost_2"))
        mask = ev.predicate_mask(p_in)
        assert not mask.any()
        assert ev.entries_scanned_in_filter == 0


class TestExplainPruning:
    def test_filter_empty_plan(self, engines):
        dev, _, _ = engines
        r = dev.execute(
            "EXPLAIN PLAN FOR SELECT COUNT(*) FROM t WHERE k = 'zzz'")
        ops = [row[0] for row in r["resultTable"]["rows"]]
        assert any("FILTER_EMPTY" in o for o in ops), ops
        assert not any("FILTER_PREDICATE" in o for o in ops)

    def test_partial_prune_line(self, engines):
        dev, _, _ = engines
        r = dev.execute(
            "EXPLAIN PLAN FOR SELECT COUNT(*) FROM t "
            "WHERE ts BETWEEN 5000 AND 5999")
        ops = [row[0] for row in r["resultTable"]["rows"]]
        assert any("PRUNE(zone-map" in o for o in ops), ops
