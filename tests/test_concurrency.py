"""Concurrent-submission parity + executor thread-safety (tier-1).

The async launch/fetch split (engine/inflight.py, DeviceExecutor.launch)
lets N queries overlap their host↔device round trips; these tests pin the
correctness half of that contract: N threads submitting a mixed query set
against one engine/server must produce results byte-identical to serial
submission — across the thread-safe executor caches, batch refcounting vs
LRU eviction, and coalesced vs solo launches.

Reference analog: a Pinot server's QueryExecutor serves many concurrent
scatter-gather requests over shared segment state; correctness under that
concurrency is assumed, here it is asserted.
"""

import threading
import time

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.engine.scheduler import QueryScheduler, TokenBucketScheduler
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment


def canonical(resp: dict) -> dict:
    """Response minus wall-clock/cache-state fields — everything else
    must be byte-identical across serial and concurrent submission
    (partialsCacheHit legitimately flips between a cold and a repeat
    execution of the same query)."""
    out = dict(resp)
    out.pop("timeUsedMs", None)
    out.pop("partialsCacheHit", None)
    # advisor stamps (ISSUE 17) are plan-state metadata: a repeat
    # execution of a trained template carries ADVISOR(...) lines the
    # cold run didn't — results stay bit-exact by construction
    out.pop("advisorDecisions", None)
    # roofline accounting (ISSUE 11) is measurement, not results: kernel
    # wall and modeled bytes differ run to run (cohort members attribute
    # the shared kernel to the leader; cache hits move zero bytes)
    for k in ("deviceBytesMoved", "deviceKernelMs", "deviceLinkMs",
              "roofline"):
        out.pop(k, None)
    return out


def run_threads(n, target):
    """Run target(i) on n threads; re-raise the first failure."""
    errors = []

    def wrapped(i):
        try:
            target(i)
        except BaseException as e:  # noqa: BLE001 — surfaced after join
            errors.append(e)

    threads = [threading.Thread(target=wrapped, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    hung = [t for t in threads if t.is_alive()]
    assert not hung, (
        f"{len(hung)} worker thread(s) hung past the join timeout "
        "(executor deadlock?)")
    if errors:
        raise errors[0]


@pytest.fixture(scope="module")
def tables(tmp_path_factory):
    """Two tables through ONE engine: 't' (dense group-by shapes) and 'hc'
    (global cartesian cardinality 2100×2100 > MAX_DENSE_GROUPS → the
    sorted/radix regime), so concurrent queries contend for the executor's
    batch LRU across regimes."""
    rng = np.random.default_rng(23)
    base = tmp_path_factory.mktemp("concseg")

    n = 4000
    cols_t = {
        "dim1": np.array([f"d{i:02d}" for i in range(40)])[
            rng.integers(0, 40, n)],
        "dim2": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
        "ivalue": rng.integers(0, 10_000, n).astype(np.int32),
        "fvalue": rng.uniform(0, 100, n).astype(np.float64),
    }
    schema_t = Schema.build(
        name="t",
        dimensions=[("dim1", DataType.STRING), ("dim2", DataType.STRING)],
        metrics=[("ivalue", DataType.INT), ("fvalue", DataType.DOUBLE)],
    )

    m = 4500
    hc1 = rng.integers(0, 2100, m).astype(np.int32)
    hc2 = rng.integers(0, 2100, m).astype(np.int32)
    # pin the GLOBAL dictionary cardinality at exactly 2100 per column
    # (2100^2 ≈ 4.41M > MAX_DENSE_GROUPS) so this really takes the sorted
    # regime regardless of random draws
    hc1[:2100] = np.arange(2100, dtype=np.int32)
    hc2[:2100] = np.arange(2100, dtype=np.int32)
    cols_hc = {
        "hc1": hc1,
        "hc2": hc2,
        "v": rng.integers(-100, 100, m).astype(np.int64),
    }
    schema_hc = Schema.build(
        name="hc",
        dimensions=[("hc1", DataType.INT), ("hc2", DataType.INT)],
        metrics=[("v", DataType.LONG)],
    )

    t_segs, hc_segs = [], []
    for i in range(3):
        sl_t = slice(i * (n // 3), (i + 1) * (n // 3) if i < 2 else n)
        build_segment(schema_t, {k: v[sl_t] for k, v in cols_t.items()},
                      str(base / f"t{i}"), segment_name=f"t{i}")
        t_segs.append(ImmutableSegment(str(base / f"t{i}")))
        sl_h = slice(i * (m // 3), (i + 1) * (m // 3) if i < 2 else m)
        build_segment(schema_hc, {k: v[sl_h] for k, v in cols_hc.items()},
                      str(base / f"hc{i}"), segment_name=f"hc{i}")
        hc_segs.append(ImmutableSegment(str(base / f"hc{i}")))
    return t_segs, hc_segs


def make_engine(t_segs, hc_segs):
    eng = QueryEngine()  # device executor auto
    for s in t_segs:
        eng.add_segment("t", s)
    for s in hc_segs:
        eng.add_segment("hc", s)
    return eng


MIXED_QUERIES = [
    # device scalar aggregation
    "SELECT COUNT(*), SUM(ivalue), MIN(ivalue), MAX(ivalue) FROM t",
    # device dense group-by (+ matmul-eligible sums)
    "SELECT dim1, COUNT(*), SUM(ivalue), AVG(fvalue) FROM t "
    "GROUP BY dim1 ORDER BY dim1 LIMIT 50",
    # filter templates with distinct literals (same compiled template)
    "SELECT COUNT(*) FROM t WHERE ivalue > 2000 AND dim2 = 'a'",
    "SELECT COUNT(*) FROM t WHERE ivalue > 7000 AND dim2 = 'c'",
    # sketchy shapes: presence + HLL
    "SELECT dim2, DISTINCTCOUNT(dim1) FROM t GROUP BY dim2 ORDER BY dim2",
    "SELECT DISTINCTCOUNTHLL(dim1) FROM t",
    # host fallback (percentile is host-only)
    "SELECT PERCENTILE(ivalue, 90) FROM t",
    # sorted/radix high-cardinality regime on the second table
    "SELECT hc1, hc2, COUNT(*), SUM(v) FROM hc GROUP BY hc1, hc2 "
    "ORDER BY COUNT(*) DESC, hc1, hc2 LIMIT 20",
]


class TestConcurrentSubmissionParity:
    def test_mixed_queries_match_serial(self, tables):
        """N threads × mixed query set == serial, byte-identical."""
        eng = make_engine(*tables)
        serial = {sql: canonical(eng.execute(sql)) for sql in MIXED_QUERIES}
        for sql, r in serial.items():
            assert not r.get("exceptions"), (sql, r)

        def worker(i):
            order = MIXED_QUERIES[i % len(MIXED_QUERIES):] + \
                MIXED_QUERIES[:i % len(MIXED_QUERIES)]
            for _ in range(2):
                for sql in order:
                    got = canonical(eng.execute(sql))
                    assert got == serial[sql], (sql, got, serial[sql])

        run_threads(6, worker)

    def test_parity_under_batch_eviction(self, tables):
        """MAX_CACHED_BATCHES=1 while two tables' queries interleave: every
        execute evicts the OTHER table's batch, so in-flight launches
        survive only through the refcount pin (_retain_launch vs _evict)."""
        eng = make_engine(*tables)
        dev = eng.device
        assert dev is not None
        dev.MAX_CACHED_BATCHES = 1  # instance override
        sql_t = "SELECT dim1, SUM(ivalue) FROM t GROUP BY dim1 ORDER BY dim1"
        sql_hc = ("SELECT hc1, COUNT(*) FROM hc GROUP BY hc1 "
                  "ORDER BY COUNT(*) DESC, hc1 LIMIT 10")
        want = {s: canonical(eng.execute(s)) for s in (sql_t, sql_hc)}

        def worker(i):
            mine = (sql_t, sql_hc) if i % 2 == 0 else (sql_hc, sql_t)
            for _ in range(3):
                for sql in mine:
                    assert canonical(eng.execute(sql)) == want[sql]

        run_threads(6, worker)
        # pins all drained: nothing left refcounted, LRU bound restored
        assert dev.inflight == 0
        assert not dev._inflight_launches
        assert len(dev._batches) <= 1

    def test_inflight_launch_pins_batch(self, tables):
        """A dispatched-but-unfetched launch keeps its batch out of LRU
        eviction; fetch() still answers correctly after churn, and the pin
        drains afterward."""
        from pinot_tpu.query.optimizer import optimize_query
        from pinot_tpu.sql.compiler import compile_query

        t_segs, hc_segs = tables
        eng = make_engine(t_segs, hc_segs)
        dev = eng.device
        dev.MAX_CACHED_BATCHES = 1
        sql = "SELECT dim2, COUNT(*), SUM(ivalue) FROM t GROUP BY dim2"
        expected = canonical(eng.execute(sql))
        q = optimize_query(compile_query(sql))
        q = eng._expand_star(q, t_segs[0])
        handle = dev.launch(q, t_segs)
        key = dev._batch_key(t_segs)
        assert dev._inflight_launches.get(key) == 1
        # churn the LRU past its cap with the other table's batch
        dev.batch_for(hc_segs)
        assert key in dev._batches, "in-flight batch was evicted"
        result = handle.fetch()
        assert int(result.stats.num_docs_scanned) > 0
        assert dev._inflight_launches.get(key) is None
        assert dev.inflight == 0
        # and the engine still answers identically afterward
        assert canonical(eng.execute(sql)) == expected


class TestLaunchCoalescing:
    COHORT_SQLS = [
        f"SELECT dim1, COUNT(*), SUM(ivalue) FROM t WHERE ivalue > {lit} "
        "GROUP BY dim1 ORDER BY SUM(ivalue) DESC, dim1 LIMIT 15"
        for lit in (100, 1500, 3000, 4500, 6000, 7500, 9000, 9900)
    ]

    def _cohort_run(self, eng):
        """Solo results first (idle executor ⇒ no windows), then the same
        8 queries released together through a forced window."""
        expected = [canonical(eng.execute(s)) for s in self.COHORT_SQLS]
        # repeats of the warm pass would hit the device partials cache
        # and never reach the coalescer — this test pins cohorts
        eng.device.partials_cache_enabled = False
        co = eng.device.coalescer
        co.force = True
        co.window_s = 0.05
        co.max_cohort = 8
        c0 = (co.cohorts_launched, co.queries_coalesced)
        try:
            barrier = threading.Barrier(len(self.COHORT_SQLS))
            got = [None] * len(self.COHORT_SQLS)

            def worker(i):
                barrier.wait()
                got[i] = canonical(eng.execute(self.COHORT_SQLS[i]))

            run_threads(len(self.COHORT_SQLS), worker)
        finally:
            co.force = False
        for i, (g, e) in enumerate(zip(got, expected)):
            assert g == e, (self.COHORT_SQLS[i], g, e)
        assert co.cohorts_launched > c0[0]
        assert co.queries_coalesced > c0[1], \
            "no query actually joined a cohort"

    def test_cohort_matches_solo(self, tables):
        """A coalesced cohort's unpacked per-query outputs equal per-query
        solo launches (same template, different literals — the dashboard
        fan-out case)."""
        self._cohort_run(make_engine(*tables))

    def test_cohort_matches_solo_on_mesh(self, tables):
        """Same contract through shard_pipeline(cohort=True): the vmapped
        cohort composes with the 8-device mesh combine."""
        from pinot_tpu.engine.device import DeviceExecutor
        from pinot_tpu.parallel.mesh import make_mesh

        t_segs, hc_segs = tables
        eng = QueryEngine(device_executor=DeviceExecutor(mesh=make_mesh(8)))
        for s in t_segs:
            eng.add_segment("t", s)
        for s in hc_segs:
            eng.add_segment("hc", s)
        self._cohort_run(eng)

    def test_sketch_final_cohort(self, tables):
        """Terminal sketch queries (device finalize AFTER the combine)
        coalesce correctly too: _finalize_sketch_outs runs per member
        under the vmap — single-device and via shard_pipeline's ``post``
        hook on the mesh."""
        from pinot_tpu.engine.device import DeviceExecutor
        from pinot_tpu.parallel.mesh import make_mesh

        t_segs, _ = tables
        sqls = [
            f"SELECT dim2, DISTINCTCOUNT(dim1), DISTINCTCOUNTHLL(dim1) "
            f"FROM t WHERE ivalue > {lit} GROUP BY dim2 ORDER BY dim2"
            for lit in (100, 3000, 6000, 9000)
        ]
        for mesh in (None, make_mesh(8)):
            eng = QueryEngine(device_executor=DeviceExecutor(mesh=mesh))
            for s in t_segs:
                eng.add_segment("t", s)
            expected = [canonical(eng.execute(s)) for s in sqls]
            eng.device.partials_cache_enabled = False  # pin cohorts, not hits
            co = eng.device.coalescer
            co.force = True
            co.window_s = 0.05
            try:
                barrier = threading.Barrier(len(sqls))
                got = [None] * len(sqls)

                def worker(i, _b=barrier, _g=got, _e=eng, _s=sqls):
                    _b.wait()
                    _g[i] = canonical(_e.execute(_s[i]))

                run_threads(len(sqls), worker)
            finally:
                co.force = False
            assert got == expected, ("mesh" if mesh else "single")

    def test_idle_executor_skips_window(self, tables):
        """No pressure ⇒ no micro-batch window: a lone query must not pay
        window latency nor mint a cohort."""
        eng = make_engine(*tables)
        co = eng.device.coalescer
        assert co.should_window(executor_inflight=1) is False
        c0 = co.cohorts_launched
        r = eng.execute(self.COHORT_SQLS[0])
        assert not r.get("exceptions")
        assert co.cohorts_launched == c0


class TestAbandonedLaunchRelease:
    def test_host_partial_failure_releases_pin(self, tables):
        """A host-segment failure between device launch and fetch must
        release the in-flight handle: otherwise the batch stays
        unevictable forever and executor.inflight (the coalescer's
        pressure signal) never drains."""
        from pinot_tpu.query.optimizer import optimize_query
        from pinot_tpu.sql.compiler import compile_query

        t_segs, _ = tables
        eng = make_engine(*tables)
        dev = eng.device
        # an upsert-masked segment forces a host partial alongside the
        # device batch; a poisoned host executor then fails the launch
        # phase AFTER the device dispatch succeeded
        class _Boom(Exception):
            pass

        def boom(q, s):
            raise _Boom()

        orig = eng.host.execute_segment
        eng.host.execute_segment = boom
        bad = t_segs[0]
        try:
            bad.valid_docs_mask = np.ones(bad.n_docs, dtype=bool)
            q = optimize_query(compile_query(
                "SELECT dim2, COUNT(*) FROM t GROUP BY dim2"))
            with pytest.raises(_Boom):
                eng.execute_query(q)
        finally:
            bad.valid_docs_mask = None
            eng.host.execute_segment = orig
        assert dev.inflight == 0, "abandoned launch leaked the pin"
        assert not dev._inflight_launches
        # and the engine recovers fully
        r = eng.execute("SELECT dim2, COUNT(*) FROM t GROUP BY dim2 "
                        "ORDER BY dim2")
        assert not r.get("exceptions"), r


class TestFetchTimeFallbackGate:
    def test_overflow_fallback_routes_through_gate(self, tables):
        """Sorted group-table overflow detected at FETCH time re-runs on
        the host THROUGH the caller's admission gate (the fetch phase is
        slot-free by design; the heavy host scan must not be)."""
        t_segs, hc_segs = tables
        eng = QueryEngine(num_groups_limit=50)  # 4500 distinct ⇒ overflow
        host_eng = QueryEngine(device_executor=None, num_groups_limit=50)
        for e in (eng, host_eng):
            for s in hc_segs:
                e.add_segment("hc", s)
        from pinot_tpu.query.optimizer import optimize_query
        from pinot_tpu.sql.compiler import compile_query

        sql = ("SELECT hc1, hc2, COUNT(*), SUM(v) FROM hc "
               "GROUP BY hc1, hc2 ORDER BY COUNT(*) DESC, hc1, hc2 LIMIT 5")
        q = optimize_query(compile_query(sql))
        gated = []

        def gate(fn):
            gated.append(1)
            return fn()

        fetch = eng.execute_segments_async(q, hc_segs, terminal=True,
                                           fallback_gate=gate)
        merged = fetch()
        assert gated, "host fallback bypassed the admission gate"
        want = host_eng.execute_segments(q, hc_segs, terminal=True)
        assert merged.stats.num_groups_limit_reached \
            == want.stats.num_groups_limit_reached
        assert canonical(eng.execute(sql)) == canonical(host_eng.execute(sql))


class TestObservabilityCounters:
    def test_counters_consistent_under_parallel_executes(self, tables):
        """CI guard: fetch_bytes_total / fetch_leaves_total / last_get_wait_s
        stay consistent under parallel executes — with coalescing off, K
        device queries of one shape account exactly K× the solo deltas."""
        eng = make_engine(*tables)
        dev = eng.device
        dev.coalescer.enabled = False
        sql = "SELECT dim1, COUNT(*), SUM(ivalue) FROM t GROUP BY dim1"
        eng.execute(sql)  # warm: compile + batch caches
        b0, l0 = dev.fetch_bytes_total, dev.fetch_leaves_total
        eng.execute(sql)
        per_bytes = dev.fetch_bytes_total - b0
        per_leaves = dev.fetch_leaves_total - l0
        assert per_bytes > 0 and 1 <= per_leaves <= 2

        b1, l1 = dev.fetch_bytes_total, dev.fetch_leaves_total
        run_threads(4, lambda i: [eng.execute(sql) for _ in range(5)])
        assert dev.fetch_bytes_total - b1 == 20 * per_bytes
        assert dev.fetch_leaves_total - l1 == 20 * per_leaves
        assert dev.last_get_wait_s is not None and dev.last_get_wait_s >= 0
        dev.coalescer.enabled = True


class TestSchedulerPressure:
    def test_fcfs_pressure_counts_running(self):
        sched = QueryScheduler(max_concurrent=2, max_queued=8)
        assert sched.pressure() == 0
        seen = sched.run(lambda: sched.pressure())
        assert seen == 1
        assert sched.pressure() == 0

    def test_tokenbucket_pressure_counts_running_and_waiting(self):
        sched = TokenBucketScheduler(max_concurrent=1, max_queued=8)
        release = threading.Event()
        inner_pressure = []

        def blocker():
            sched.run(lambda: (inner_pressure.append(sched.pressure()),
                               release.wait(5)))

        t = threading.Thread(target=blocker)
        t.start()
        for _ in range(100):
            if inner_pressure:
                break
            time.sleep(0.01)
        waiter = threading.Thread(
            target=lambda: sched.run(lambda: None, queue_timeout_s=5))
        waiter.start()
        for _ in range(100):
            if sched.pressure() >= 2:
                break
            time.sleep(0.01)
        assert sched.pressure() >= 2  # one running + one queued
        release.set()
        t.join(5)
        waiter.join(5)
        assert sched.pressure() == 0


class TestServerConcurrentSubmission:
    def test_server_parity_and_compile_bound(self, tables, tmp_path):
        """End-to-end: N threads through a real ServerInstance (gRPC
        handler path: compile semaphore → scheduler slot for the launch
        phase → slot-free fetch) answer byte-identically to serial, and
        the compileQueueMs timer records every compile."""
        from pinot_tpu.cluster.registry import ClusterRegistry
        from pinot_tpu.server.server import ServerInstance
        from pinot_tpu.transport.grpc_transport import make_instance_request

        t_segs, _ = tables
        registry = ClusterRegistry()
        server = ServerInstance("s0", registry, str(tmp_path / "sd"),
                                max_concurrent_queries=4)
        for s in t_segs:
            server.engine.add_segment("t", s)
        seg_names = [s.name for s in t_segs]
        try:
            from pinot_tpu.engine.datatable import decode

            sqls = [
                "SELECT dim1, COUNT(*), SUM(ivalue) FROM t GROUP BY dim1 "
                "ORDER BY dim1 LIMIT 50",
                "SELECT COUNT(*) FROM t WHERE dim2 = 'b'",
                "SELECT PERCENTILE(ivalue, 50) FROM t",
            ]

            def submit(sql, rid):
                payload = server._handle_submit(
                    make_instance_request(sql, seg_names, rid))
                res = decode(payload)
                # scheduler wait + cpu accounting are load-dependent
                res.stats.scheduler_wait_ms = 0.0
                res.stats.thread_cpu_time_ns = 0
                return res

            serial = {sql: submit(sql, i) for i, sql in enumerate(sqls)}

            def worker(i):
                for j, sql in enumerate(sqls):
                    got = submit(sql, 100 + i * 10 + j)
                    want = serial[sql]
                    assert got.shape == want.shape
                    assert str(got.agg_partials) == str(want.agg_partials)
                    assert got.stats.num_docs_scanned == \
                        want.stats.num_docs_scanned

            run_threads(6, worker)
            snap = server.metrics.snapshot()
            timer = snap["timers"].get("server.compileQueueMs")
            assert timer is not None and \
                timer["count"] >= len(sqls) * 7  # serial + 6 threads
        finally:
            # the server was never start()ed (its sync loop would unload
            # the directly-injected segments); drop just its gauges so the
            # process-global registry doesn't pin this instance
            server.metrics.remove_gauge("segmentsLoaded", tag="s0")
            server.metrics.remove_gauge("schedulerRejected", tag="s0")
