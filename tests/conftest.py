"""Test environment: force JAX onto CPU with 8 virtual devices.

Must run before the first ``import jax`` anywhere in the test process so the
multi-chip sharding paths (parallel/mesh.py) are exercised on a virtual
8-device mesh, per the driver's dryrun contract.
"""

import os

# force, don't setdefault: the interactive environment pins JAX_PLATFORMS to
# the real TPU backend, and tests must not contend for the chip
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# sitecustomize.py (axon TPU tunnel) imports jax at interpreter startup,
# before this file runs — env mutation alone is too late, the config values
# must be updated on the already-imported module
import jax

jax.config.update("jax_platforms", "cpu")
try:
    # newer jax spells the virtual-device count as a config option; older
    # releases (<= 0.4.x) only honor --xla_force_host_platform_device_count,
    # which is already set above — a missing option must not kill collection
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def baseball_schema():
    return Schema.build(
        name="baseballStats",
        dimensions=[
            ("playerName", DataType.STRING),
            ("teamID", DataType.STRING),
            ("league", DataType.STRING),
            ("yearID", DataType.INT),
        ],
        metrics=[
            ("runs", DataType.INT),
            ("hits", DataType.INT),
            ("homeRuns", DataType.INT),
            ("salary", DataType.DOUBLE),
        ],
    )


def make_baseball_columns(rng, n=5000):
    players = np.array([f"player_{i:03d}" for i in range(200)])
    teams = np.array([f"team_{i}" for i in range(30)])
    leagues = np.array(["AL", "NL"])
    return {
        "playerName": players[rng.integers(0, len(players), n)],
        "teamID": teams[rng.integers(0, len(teams), n)],
        "league": leagues[rng.integers(0, 2, n)],
        "yearID": rng.integers(1980, 2020, n).astype(np.int32),
        "runs": rng.integers(0, 150, n).astype(np.int32),
        "hits": rng.integers(0, 200, n).astype(np.int32),
        "homeRuns": rng.integers(0, 60, n).astype(np.int32),
        "salary": np.round(rng.uniform(1e4, 1e7, n), 2),
    }


@pytest.fixture(scope="session")
def baseball_columns(rng):
    return make_baseball_columns(rng)


@pytest.fixture(scope="session")
def baseball_segment(tmp_path_factory, baseball_schema, baseball_columns):
    from pinot_tpu.common.table_config import IndexingConfig, TableConfig
    from pinot_tpu.storage.creator import build_segment

    out = tmp_path_factory.mktemp("segments") / "baseball_0"
    cfg = TableConfig(
        table_name="baseballStats",
        indexing=IndexingConfig(
            inverted_index_columns=["teamID", "league"],
            bloom_filter_columns=["playerName"],
        ),
    )
    return build_segment(baseball_schema, baseball_columns, str(out), cfg, "baseball_0")
