"""Large-scale differential harness: device == host == sqlite oracle.

The round-3 verdict's tier-2 acceptance: a seeded multi-million-row,
multi-segment table (MV entries, nulls, an evolved schema column, an
upsert validDocIds mask) where every query shape is executed through the
DEVICE path, the HOST path, and a sqlite oracle, at tolerances derived
from the documented exactness bounds — the scale where padding, f32 dict
decodes, sorted-regime tables and two-stage superblock boundaries
actually bite (the reference's H2 cross-check,
ClusterIntegrationTestUtils).

Row count defaults to 5M (PINOT_TPU_DIFF_ROWS overrides — e.g. 500000 for
a quick local run).
"""

import math
import os
import sqlite3

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment

N_ROWS = int(os.environ.get("PINOT_TPU_DIFF_ROWS", 5_000_000))
N_SEGMENTS = 4
# Under numGroupsLimit (100k) so single-dim group-bys compare exactly
# across plans; devid x code = 4.5M crosses MAX_DENSE_GROUPS (4.19M) so
# that shape exercises the SORTED regime. Results ABOVE numGroupsLimit are
# plan-dependent-partial by reference contract (numGroupsLimitReached) —
# covered by the flag test, not by row equality.
HIGH_CARD = 90_000


def _build(tmp_path_factory):
    rng = np.random.default_rng(2024)
    n = N_ROWS
    cols = {
        "site": np.array([f"s{i:02d}" for i in range(24)])[
            rng.integers(0, 24, n)],
        "devid": rng.integers(0, HIGH_CARD, n).astype(np.int32),
        "code": rng.integers(0, 50, n).astype(np.int32),
        # wide-range metric: exercises two-stage superblock sizing
        "amount": rng.integers(0, 1_000_000, n).astype(np.int64),
        "ratio": np.round(rng.uniform(0, 10, n), 4),
        # nullable metric: ~10% null (stored as type default 0 + null vector)
        "opt": rng.integers(1, 100, n).astype(np.int32),
    }
    null_mask = rng.random(n) < 0.1
    opt_vals = cols["opt"].astype(object)
    opt_vals[null_mask] = None
    cols["opt"] = opt_vals
    # MV column, 0-3 entries per row
    tagpool = np.array(["red", "green", "blue", "gold"])
    lens = rng.integers(0, 4, n)
    mv = [list(tagpool[rng.choice(4, k, replace=False)]) for k in lens]
    cols["tags"] = mv

    schema = Schema.build(
        name="events",
        dimensions=[("site", DataType.STRING), ("devid", DataType.INT),
                    ("code", DataType.INT)],
        multi_value_dimensions=[("tags", DataType.STRING)],
        metrics=[("amount", DataType.LONG), ("ratio", DataType.DOUBLE),
                 ("opt", DataType.INT)],
    )
    cfg = TableConfig(table_name="events", indexing=IndexingConfig(
        inverted_index_columns=["site"]))

    base = tmp_path_factory.mktemp("diff")
    dev_eng = QueryEngine()  # device executor (CPU backend in tests)
    host_eng = QueryEngine(device_executor=None)
    per = n // N_SEGMENTS
    valid_sql_rows = np.ones(n, dtype=bool)
    for i in range(N_SEGMENTS):
        sl = slice(i * per, n if i == N_SEGMENTS - 1 else (i + 1) * per)
        part = {k: (v[sl] if not isinstance(v, list) else v[sl])
                for k, v in cols.items()}
        d = str(base / f"seg{i}")
        build_segment(schema, part, d, cfg, f"events_{i}")
        for eng in (dev_eng, host_eng):
            seg = ImmutableSegment(d)
            if i == N_SEGMENTS - 1:
                # upsert validDocIds mask on the last segment: every odd doc
                # superseded — device must route this segment to the host
                # scan path and results must exclude those rows
                m = np.ones(seg.n_docs, dtype=bool)
                m[1::2] = False
                seg.valid_docs_mask = m
            eng.add_segment("events", seg)
    seg_rows = np.arange(n)
    last = slice((N_SEGMENTS - 1) * per, n)
    local = seg_rows[last] - (N_SEGMENTS - 1) * per
    valid_sql_rows[last] = (local % 2) == 0

    con = sqlite3.connect(":memory:")
    con.execute("CREATE TABLE events (site TEXT, devid INT, code INT, "
                "amount INT, ratio REAL, opt INT, ntags INT)")
    con.executemany(
        "INSERT INTO events VALUES (?,?,?,?,?,?,?)",
        [
            (cols["site"][i], int(cols["devid"][i]), int(cols["code"][i]),
             int(cols["amount"][i]), float(cols["ratio"][i]),
             None if cols["opt"][i] is None else int(cols["opt"][i]),
             len(mv[i]))
            for i in np.nonzero(valid_sql_rows)[0]
        ],
    )
    con.commit()
    return dev_eng, host_eng, con


@pytest.fixture(scope="module")
def harness(tmp_path_factory):
    return _build(tmp_path_factory)


# (pinot sql, sqlite sql or None=same, float_cols set by position)
QUERIES = [
    # scalar aggregations, wide-range sums (superblock boundaries)
    ("SELECT COUNT(*), SUM(amount), MIN(amount), MAX(amount) FROM events",
     None),
    ("SELECT SUM(amount) FROM events WHERE amount BETWEEN 250000 AND 750000",
     None),
    ("SELECT COUNT(*), SUM(ratio) FROM events WHERE site IN ('s03','s11','s17')",
     None),
    # group-by: dense low-card
    ("SELECT site, COUNT(*), SUM(amount), AVG(ratio) FROM events "
     "GROUP BY site ORDER BY site LIMIT 30", None),
    # two-dim dense
    ("SELECT site, code, SUM(amount) FROM events WHERE code < 10 "
     "GROUP BY site, code ORDER BY site, code LIMIT 300", None),
    # high-card dense (devid alone fits the dense regime)
    ("SELECT devid, COUNT(*), SUM(amount) FROM events GROUP BY devid "
     "ORDER BY COUNT(*) DESC, devid LIMIT 20", None),
    # high-card SORTED regime (devid x code crosses MAX_DENSE_GROUPS;
    # matched combos kept under numGroupsLimit via the filters)
    ("SELECT devid, code, COUNT(*), SUM(amount), MIN(amount), MAX(amount) "
     "FROM events WHERE devid < 20000 AND code = 7 "
     "GROUP BY devid, code ORDER BY COUNT(*) DESC, devid, code LIMIT 25",
     None),
    # nulls: IS NULL / IS NOT NULL
    ("SELECT COUNT(*) FROM events WHERE opt IS NULL", None),
    ("SELECT site, COUNT(*) FROM events WHERE opt IS NOT NULL "
     "GROUP BY site ORDER BY site LIMIT 30", None),
    # MV: match-any predicate + per-doc transform
    ("SELECT COUNT(*) FROM events WHERE tags = 'gold'",
     "SELECT SUM(CASE WHEN ntags >= 1 THEN 0 ELSE 0 END) + "
     "(SELECT COUNT(*) FROM events WHERE 0) FROM events WHERE 0"),
    ("SELECT SUM(ARRAYLENGTH(tags)) FROM events",
     "SELECT SUM(ntags) FROM events"),
    # distinct count exact
    ("SELECT DISTINCTCOUNT(code) FROM events WHERE site = 's05'",
     "SELECT COUNT(DISTINCT code) FROM events WHERE site = 's05'"),
    # transforms in filter + select
    ("SELECT TIMECONVERT(amount, 'MILLISECONDS', 'SECONDS'), COUNT(*) "
     "FROM events WHERE amount < 5000 GROUP BY "
     "TIMECONVERT(amount, 'MILLISECONDS', 'SECONDS') "
     "ORDER BY TIMECONVERT(amount, 'MILLISECONDS', 'SECONDS') LIMIT 10",
     "SELECT amount / 1000, COUNT(*) FROM events WHERE amount < 5000 "
     "GROUP BY amount / 1000 ORDER BY amount / 1000 LIMIT 10"),
]


def _norm(v):
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, (float, np.floating)):
        return float(v)
    return v


def _compare(rows_a, rows_b, label, rel=1e-4):
    assert len(rows_a) == len(rows_b), (
        f"{label}: {len(rows_a)} rows != {len(rows_b)}")
    for i, (ra, rb) in enumerate(zip(rows_a, rows_b)):
        assert len(ra) == len(rb), (label, i, ra, rb)
        for a, b in zip(ra, rb):
            a, b = _norm(a), _norm(b)
            if isinstance(a, float) or isinstance(b, float):
                a = 0.0 if a is None else float(a)
                b = 0.0 if b is None else float(b)
                assert math.isclose(a, b, rel_tol=rel, abs_tol=1e-6), (
                    label, i, ra, rb)
            else:
                assert a == b, (label, i, ra, rb)


def _rows(engine, sql):
    r = engine.execute(sql)
    assert not r.get("exceptions"), (sql, r["exceptions"])
    return [tuple(row) for row in r["resultTable"]["rows"]]


class TestDifferential:
    @pytest.mark.parametrize("idx", range(len(QUERIES)))
    def test_device_host_oracle_agree(self, harness, idx):
        dev_eng, host_eng, con = harness
        pinot_sql, sqlite_sql = QUERIES[idx]
        got_dev = _rows(dev_eng, pinot_sql)
        got_host = _rows(host_eng, pinot_sql)
        # device vs host must agree at float tolerance (f32 dict decode is
        # the documented divergence; int aggregates are exact)
        _compare(got_dev, got_host, f"dev-vs-host: {pinot_sql}")
        if sqlite_sql is None:
            sqlite_sql = pinot_sql
        if "WHERE 0" in sqlite_sql:
            return  # MV predicate has no faithful sqlite form; dev==host is the check
        want = [tuple(r) for r in con.execute(sqlite_sql).fetchall()]
        _compare(got_dev, want, f"dev-vs-sqlite: {pinot_sql}")

    def test_above_limit_sets_flag_on_both_paths(self, harness):
        """Past numGroupsLimit, results are plan-dependent-partial by
        reference contract — both backends must SAY so
        (numGroupsLimitReached), not silently diverge (the round-4 bug
        this harness caught at 5M rows)."""
        dev_eng, host_eng, _ = harness
        # a SET numGroupsLimit below any segment's group count forces the
        # cap on BOTH paths regardless of the harness scale (the host's cap
        # is per segment, like the reference's group-key generator)
        sql = ("SET numGroupsLimit = 500; "
               "SELECT devid, site, COUNT(*) FROM events "
               "GROUP BY devid, site ORDER BY COUNT(*) DESC LIMIT 5")
        for eng in (dev_eng, host_eng):
            r = eng.execute(sql)
            assert not r.get("exceptions"), r
            assert r["numGroupsLimitReached"] is True, r
        # and an under-limit query does NOT set it
        r = dev_eng.execute("SELECT site, COUNT(*) FROM events GROUP BY site")
        assert r["numGroupsLimitReached"] is False

    def test_hll_device_equals_host_exactly(self, harness):
        """HLL registers must be BIT-IDENTICAL across backends (same value
        hashes both sides) — compared device vs host, not vs sqlite."""
        dev_eng, host_eng, _ = harness
        sql = ("SELECT site, DISTINCTCOUNTHLL(devid) FROM events "
               "GROUP BY site ORDER BY site LIMIT 30")
        assert _rows(dev_eng, sql) == _rows(host_eng, sql)

    def test_injected_superblock_off_by_one_is_caught(self, harness,
                                                      monkeypatch):
        """The harness must FAIL when the two-stage scatter misassigns one
        row per block boundary (the regression class this suite exists
        for)."""
        import jax.numpy as jnp

        from pinot_tpu.engine.device import DeviceExecutor
        from pinot_tpu.ops import agg as agg_ops

        dev_eng, host_eng, _ = harness
        real = agg_ops.group_sum

        def broken_group_sum(gids, values, num_groups, rows_per_block=None):
            flat_g = gids.reshape(-1)
            v = values.reshape(-1)
            n = v.shape[0]
            rpb = rows_per_block or 4096
            nb = (n + rpb - 1) // rpb
            stride = num_groups + 1
            if nb <= 1 or nb * stride >= 2**31:
                out = jnp.zeros(num_groups + 1, dtype=jnp.int64).at[flat_g].add(
                    v.astype(jnp.int64))
                return out[:num_groups]
            # INJECTED BUG: row i lands in block (i+1)//rpb — every block
            # boundary row is summed in the wrong superblock partial; the
            # per-group totals stay correct ONLY if the reduce is right,
            # but int32 stage-1 slots now alias across groups
            block = (jnp.arange(n, dtype=jnp.int32) + 1) // rpb
            slot = block * stride + (flat_g + 1) % stride
            part = jnp.zeros(nb * stride, dtype=jnp.int32).at[slot].add(
                v.astype(jnp.int32))
            out = jnp.sum(part.reshape(nb, stride), axis=0, dtype=jnp.int64)
            return out[:num_groups]

        monkeypatch.setattr(agg_ops, "group_sum", broken_group_sum)
        # fresh executor: the pipeline cache must not serve the correct
        # compiled kernels
        dev_eng.device = DeviceExecutor()
        try:
            sql = ("SELECT site, SUM(amount) FROM events GROUP BY site "
                   "ORDER BY site LIMIT 30")
            got_dev = _rows(dev_eng, sql)
            got_host = _rows(host_eng, sql)
            with pytest.raises(AssertionError):
                _compare(got_dev, got_host, "injected")
        finally:
            monkeypatch.setattr(agg_ops, "group_sum", real)
            dev_eng.device = DeviceExecutor()
