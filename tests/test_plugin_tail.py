"""Plugin/client tail: pulsar stream (faked client), thrift +
confluent-avro input formats, WebHDFS filesystem (faked REST), the
SQLAlchemy dialect (faked sqlalchemy), and SHOW TABLES.

Reference analogs: pinot-plugins/pinot-stream-ingestion/pinot-pulsar,
pinot-input-format/pinot-thrift + pinot-confluent-avro,
pinot-file-system/pinot-hdfs, pinot-clients/pinot-jdbc-client.
"""

import json
import sys
import types

import numpy as np
import pytest

from pinot_tpu.common.table_config import StreamConfig


# ---------------------------------------------------------------------------
# thrift input format
# ---------------------------------------------------------------------------


def test_thrift_roundtrip():
    from pinot_tpu.ingestion.thrift_io import (
        binary_decoder_for,
        encode_record,
        parse_field_map,
    )

    fmap = parse_field_map("1:name, 2:age, 3:score, 4:tags")
    assert fmap == {1: ("name", False), 2: ("age", False),
                    3: ("score", False), 4: ("tags", False)}
    row = {"name": "ann", "age": 41, "score": 2.5, "tags": ["x", "y"]}
    payload = encode_record(row, fmap)
    decode = binary_decoder_for("1:name,2:age,3:score,4:tags")
    assert decode(payload) == row


def test_thrift_binary_annotation_is_type_stable():
    """#bytes-annotated fields stay bytes even when the payload happens to
    be valid UTF-8 (content-dependent str-or-bytes would be type-unstable
    within one column)."""
    from pinot_tpu.ingestion.thrift_io import binary_decoder_for, encode_record

    payload = encode_record({"s": "text", "b": b"abc"}, {1: "s", 2: "b"})
    out = binary_decoder_for("1:s,2:b#bytes")(payload)
    assert out == {"s": "text", "b": b"abc"}
    assert isinstance(out["b"], bytes) and isinstance(out["s"], str)


def test_thrift_skips_undeclared_fields():
    from pinot_tpu.ingestion.thrift_io import binary_decoder_for, encode_record

    payload = encode_record({"a": 1, "b": "keep", "c": 9.5},
                            {1: "a", 2: "b", 3: "c"})
    # decoder only declares field 2: others are consumed, not surfaced
    assert binary_decoder_for("2:b")(payload) == {"b": "keep"}


def test_thrift_stream_decoder_registration():
    from pinot_tpu.stream.spi import get_decoder

    cfg = StreamConfig(stream_type="memory", topic="t", decoder="thrift",
                       properties={"thrift.field.map": "1:k,2:v"})
    from pinot_tpu.ingestion.thrift_io import encode_record

    d = get_decoder("thrift", cfg)
    assert d(encode_record({"k": "a", "v": 7}, {1: "k", 2: "v"})) \
        == {"k": "a", "v": 7}


def test_thrift_truncated_raises():
    from pinot_tpu.ingestion.thrift_io import binary_decoder_for, encode_record

    payload = encode_record({"a": "hello"}, {1: "a"})
    with pytest.raises(EOFError):
        binary_decoder_for("1:a")(payload[:-3])


# ---------------------------------------------------------------------------
# confluent-avro input format
# ---------------------------------------------------------------------------

SCHEMA = {"type": "record", "name": "r", "fields": [
    {"name": "k", "type": "string"}, {"name": "v", "type": "long"}]}


def test_confluent_avro_inline_schema():
    from pinot_tpu.ingestion.confluent_avro import (
        ConfluentAvroDecoder,
        encode_confluent,
    )

    dec = ConfluentAvroDecoder(inline_schemas={7: json.dumps(SCHEMA)})
    msg = encode_confluent(7, SCHEMA, {"k": "x", "v": 42})
    assert dec(msg) == {"k": "x", "v": 42}
    with pytest.raises(ValueError):
        dec(b"\x01junk")  # wrong magic
    with pytest.raises(KeyError):
        dec(encode_confluent(8, SCHEMA, {"k": "x", "v": 1}))  # unknown id


def test_confluent_avro_registry_fetch(monkeypatch):
    import urllib.request

    from pinot_tpu.ingestion import confluent_avro as ca

    class FakeResp:
        def __init__(self, body):
            self.body = body

        def read(self):
            return json.dumps(self.body).encode()

        def __enter__(self):
            return self

        def __exit__(self, *a):
            pass

    calls = []

    def fake_urlopen(url, timeout=None):
        calls.append(url)
        return FakeResp({"schema": json.dumps(SCHEMA)})

    monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
    dec = ca.ConfluentAvroDecoder(registry_url="http://reg:8081")
    msg = ca.encode_confluent(11, SCHEMA, {"k": "y", "v": 5})
    assert dec(msg) == {"k": "y", "v": 5}
    assert dec(msg) == {"k": "y", "v": 5}  # cached: one fetch only
    assert calls == ["http://reg:8081/schemas/ids/11"]


def test_confluent_decoder_registration():
    from pinot_tpu.stream.spi import get_decoder

    cfg = StreamConfig(
        stream_type="memory", topic="t", decoder="confluent-avro",
        properties={"schema.registry.schemas.3": json.dumps(SCHEMA)})
    from pinot_tpu.ingestion.confluent_avro import encode_confluent

    d = get_decoder("confluent-avro", cfg)
    assert d(encode_confluent(3, SCHEMA, {"k": "z", "v": 9})) \
        == {"k": "z", "v": 9}


# ---------------------------------------------------------------------------
# pulsar stream plugin (faked pulsar module)
# ---------------------------------------------------------------------------


class FakeMessageId:
    earliest = "EARLIEST"

    def __init__(self, partition, ledger, entry, batch):
        self._l, self._e, self._b = ledger, entry, batch

    def ledger_id(self):
        return self._l

    def entry_id(self):
        return self._e

    def batch_index(self):
        return self._b


class FakeMsg:
    def __init__(self, mid, payload):
        self._mid, self._payload = mid, payload

    def message_id(self):
        return self._mid

    def data(self):
        return self._payload

    def partition_key(self):
        return ""

    def publish_timestamp(self):
        return 1234


class FakeReader:
    """Reads from the LIVE FakeClient.msgs list (a real pulsar reader
    streams messages published after it was created)."""

    def __init__(self, start, inclusive):
        from pinot_tpu.stream.pulsar_stream import pack_message_id

        if start == "EARLIEST":
            self._lo = -1
        else:
            self._lo = pack_message_id(start._l, start._e, start._b)
            if inclusive:
                self._lo -= 1

    def read_next(self, timeout_millis=None):
        from pinot_tpu.stream.pulsar_stream import pack_message_id

        pending = sorted(
            (pack_message_id(m.message_id()._l, m.message_id()._e,
                             m.message_id()._b), m)
            for m in FakeClient.msgs
            if pack_message_id(m.message_id()._l, m.message_id()._e,
                               m.message_id()._b) > self._lo)
        if not pending:
            raise TimeoutError("no more")
        packed, m = pending[0]
        self._lo = packed
        return m

    def close(self):
        pass


class FakeClient:
    msgs: list = []

    def __init__(self, url, **kw):
        pass

    def get_topic_partitions(self, topic):
        return [topic]

    def create_reader(self, topic, start, start_message_id_inclusive=False):
        return FakeReader(start, start_message_id_inclusive)

    def close(self):
        pass


def test_pulsar_plugin(monkeypatch):
    fake = types.ModuleType("pulsar")
    fake.Client = FakeClient
    fake.MessageId = FakeMessageId
    monkeypatch.setitem(sys.modules, "pulsar", fake)

    from pinot_tpu.stream.pulsar_stream import (
        PulsarConsumerFactory,
        pack_message_id,
        unpack_message_id,
    )
    from pinot_tpu.stream.spi import StreamPartitionMsgOffset

    # packing round-trips and orders like MessageId comparison
    assert unpack_message_id(pack_message_id(5, 100, 2)) == (5, 100, 2)
    assert unpack_message_id(pack_message_id(5, 100, -1)) == (5, 100, -1)
    assert pack_message_id(5, 100, -1) < pack_message_id(5, 100, 0)
    assert pack_message_id(5, 999, 3) < pack_message_id(6, 0, -1)

    FakeClient.msgs = [
        FakeMsg(FakeMessageId(-1, 1, i, -1), json.dumps({"i": i}).encode())
        for i in range(5)
    ]
    cfg = StreamConfig(stream_type="pulsar", topic="t", decoder="json")
    factory = PulsarConsumerFactory(cfg)
    assert factory.partition_count() == 1
    consumer = factory.create_partition_consumer(0)
    batch = consumer.fetch_messages(StreamPartitionMsgOffset(0), 100)
    assert len(batch) == 5
    # resume from next_offset: nothing new
    again = consumer.fetch_messages(batch.next_offset, 100)
    assert len(again) == 0
    # publish more; resume picks up only the new ones
    FakeClient.msgs.append(
        FakeMsg(FakeMessageId(-1, 2, 0, -1), b'{"i": 99}'))
    more = consumer.fetch_messages(batch.next_offset, 100)
    assert len(more) == 1 and json.loads(more.messages[0].payload)["i"] == 99


def test_pulsar_entry_bound_validated_at_construction(monkeypatch):
    """An operator who raised managedLedgerMaxEntriesPerLedger past the
    packed-offset entry_id bound must be rejected when the factory /
    consumer is BUILT (declared via the pulsar.max.entries.per.ledger
    property), not via a mid-consume ValueError after ingest started."""
    fake = types.ModuleType("pulsar")
    fake.Client = FakeClient
    fake.MessageId = FakeMessageId
    monkeypatch.setitem(sys.modules, "pulsar", fake)

    from pinot_tpu.stream.pulsar_stream import (
        PulsarConsumerFactory,
        _ENTRY_BITS,
    )

    over = StreamConfig(
        stream_type="pulsar", topic="t", decoder="json",
        properties={"pulsar.max.entries.per.ledger": str(1 << 21)})
    with pytest.raises(ValueError, match="entry_id bound"):
        PulsarConsumerFactory(over)

    # at or under the bound (the broker default is 50k): accepted, and
    # consumer construction passes the same gate
    under = StreamConfig(
        stream_type="pulsar", topic="t", decoder="json",
        properties={"pulsar.max.entries.per.ledger": str(1 << _ENTRY_BITS)})
    factory = PulsarConsumerFactory(under)
    assert factory.create_partition_consumer(0) is not None

    # undeclared config: the per-message pack guard stays the backstop
    undeclared = StreamConfig(stream_type="pulsar", topic="t", decoder="json")
    PulsarConsumerFactory(undeclared).create_partition_consumer(0)


def test_pulsar_gating_error():
    import builtins

    real_import = builtins.__import__

    def no_pulsar(name, *a, **k):
        if name == "pulsar":
            raise ImportError("nope")
        return real_import(name, *a, **k)

    sys.modules.pop("pulsar", None)
    builtins.__import__ = no_pulsar
    try:
        from pinot_tpu.stream.pulsar_stream import PulsarConsumerFactory

        cfg = StreamConfig(stream_type="pulsar", topic="t", decoder="json")
        with pytest.raises(RuntimeError, match="pulsar-client"):
            PulsarConsumerFactory(cfg).partition_count()
    finally:
        builtins.__import__ = real_import


# ---------------------------------------------------------------------------
# WebHDFS filesystem (faked REST endpoints)
# ---------------------------------------------------------------------------


def test_hdfs_fs(monkeypatch, tmp_path):
    import urllib.error
    import urllib.request

    from pinot_tpu.storage.hdfsfs import HdfsFS

    store: dict = {}  # hdfs path -> bytes (files) | None (dirs)

    class Resp:
        def __init__(self, body=b"{}"):
            self.body = body
            self.headers = {}
            self._pos = 0

        def read(self, n=None):
            if n is None:
                out, self._pos = self.body[self._pos:], len(self.body)
            else:
                out = self.body[self._pos: self._pos + n]
                self._pos += len(out)
            return out

        def __enter__(self):
            return self

        def __exit__(self, *a):
            pass

    def fake_urlopen(req, timeout=None):
        url = req.full_url if hasattr(req, "full_url") else req
        method = req.get_method() if hasattr(req, "get_method") else "GET"
        path, _, qs = url.partition("?")
        path = path.split("/webhdfs/v1", 1)[1]
        op = [p.split("=", 1)[1] for p in qs.split("&")
              if p.startswith("op=")][0]
        if op == "MKDIRS":
            store[path] = None
            return Resp(b'{"boolean": true}')
        if op == "DELETE":
            for k in [k for k in store if k == path
                      or k.startswith(path.rstrip("/") + "/")]:
                store.pop(k)
            return Resp(b'{"boolean": true}')
        if op == "GETFILESTATUS":
            if path in store:
                t = "DIRECTORY" if store[path] is None else "FILE"
                return Resp(json.dumps(
                    {"FileStatus": {"type": t, "pathSuffix": ""}}).encode())
            # real HDFS materializes parent dirs implicitly on CREATE
            if any(k.startswith(path.rstrip("/") + "/") for k in store):
                return Resp(json.dumps({"FileStatus": {
                    "type": "DIRECTORY", "pathSuffix": ""}}).encode())
            raise urllib.error.HTTPError(url, 404, "nf", {}, None)
        if op == "LISTSTATUS":
            pfx = path.rstrip("/") + "/"
            names = {}
            for k, v in store.items():
                if k.startswith(pfx):
                    top = k[len(pfx):].split("/", 1)[0]
                    deeper = "/" in k[len(pfx):]
                    names[top] = "DIRECTORY" if (deeper or (
                        store.get(pfx + top, b"") is None)) else "FILE"
            return Resp(json.dumps({"FileStatuses": {"FileStatus": [
                {"pathSuffix": n, "type": t} for n, t in names.items()
            ]}}).encode())
        if op == "CREATE":
            if "dn=1" not in qs:
                # model the namenode's two-step protocol: 307 to a datanode
                raise urllib.error.HTTPError(
                    url, 307, "redirect",
                    {"Location": f"{url}&dn=1"}, None)
            d = req.data
            if hasattr(d, "read"):  # streamed file-like PUT body
                d = d.read()
            store[path] = d if d is not None else b""
            return Resp(b"")
        if op == "OPEN":
            if path not in store or store[path] is None:
                raise urllib.error.HTTPError(url, 404, "nf", {}, None)
            return Resp(store[path])
        raise AssertionError(op)

    monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
    fs = HdfsFS()
    base = "hdfs://nn:9870/segments/seg_0"
    assert not fs.exists(base)
    # upload a directory
    local = tmp_path / "seg"
    (local / "sub").mkdir(parents=True)
    (local / "a.bin").write_bytes(b"AAA")
    (local / "sub" / "b.bin").write_bytes(b"BB")
    fs.copy(str(local), base)
    assert fs.exists(base)
    assert fs.list_files(base) == ["a.bin", "sub"]
    # download it back
    out = tmp_path / "down"
    fs.copy(base, str(out))
    assert (out / "a.bin").read_bytes() == b"AAA"
    assert (out / "sub" / "b.bin").read_bytes() == b"BB"
    fs.delete(base)
    assert not fs.exists(base)


def test_hdfs_registered():
    from pinot_tpu.common.plugins import plugin_registry

    assert "hdfs" in plugin_registry.available("fs")


# ---------------------------------------------------------------------------
# SHOW TABLES + SQLAlchemy dialect
# ---------------------------------------------------------------------------


def _mini_cluster(tmp_path):
    from pinot_tpu.broker.broker import Broker
    from pinot_tpu.cluster.registry import ClusterRegistry
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.server.server import ServerInstance
    from pinot_tpu.storage.creator import build_segment

    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "ds"))
    server = ServerInstance("s0", registry, str(tmp_path / "srv"),
                            device_executor=None)
    server.start()
    broker = Broker(registry)
    schema = Schema.build(name="trips", dimensions=[("city", DataType.STRING)],
                          metrics=[("fare", DataType.LONG)])
    controller.add_table(TableConfig(table_name="trips"), schema)
    d = str(tmp_path / "up")
    build_segment(schema, {"city": np.array(["ny", "sf"] * 50),
                           "fare": np.arange(100, dtype=np.int64)}, d,
                  segment_name="trips_s0")
    controller.upload_segment("trips", d)
    import time

    deadline = time.time() + 10
    while time.time() < deadline:
        r = broker.execute("SELECT COUNT(*) FROM trips")
        if not r.get("exceptions") and r["resultTable"]["rows"][0][0] == 100:
            break
        time.sleep(0.05)
    return broker, server


def test_show_tables_and_dbapi_catalog(tmp_path):
    broker, server = _mini_cluster(tmp_path)
    try:
        r = broker.execute("SHOW TABLES")
        assert r["resultTable"]["rows"] == [["trips"]]
        from pinot_tpu.client import connect

        conn = connect(broker=broker)
        cur = conn.cursor()
        cur.execute("SHOW TABLES;")
        assert cur.fetchall() == [("trips",)]
        # LIMIT 0 column probe (the dialect's get_columns path)
        cur.execute("SELECT * FROM trips LIMIT 0")
        assert [d[0] for d in cur.description] == ["city", "fare"]
        assert [d[1] for d in cur.description] == ["STRING", "LONG"]
    finally:
        server.stop()


def test_sqlalchemy_dialect_with_fake_sa(tmp_path, monkeypatch):
    """The dialect's surface works against a minimal faked sqlalchemy:
    connect-args parsing, dbapi hookup, table/column reflection."""
    sa = types.ModuleType("sqlalchemy")
    sa_types = types.SimpleNamespace(
        INTEGER=lambda: "INTEGER", BIGINT=lambda: "BIGINT",
        FLOAT=lambda: "FLOAT", VARCHAR=lambda: "VARCHAR",
        BOOLEAN=lambda: "BOOLEAN", TIMESTAMP=lambda: "TIMESTAMP",
        LargeBinary=lambda: "LargeBinary", JSON=lambda: "JSON",
        Numeric=lambda: "Numeric")
    sa.types = sa_types
    registered = {}
    sa.dialects = types.SimpleNamespace(registry=types.SimpleNamespace(
        register=lambda name, mod, attr: registered.update({name: (mod, attr)})))
    engine_mod = types.ModuleType("sqlalchemy.engine")
    default_mod = types.ModuleType("sqlalchemy.engine.default")

    class DefaultDialect:
        def __init__(self, *a, **k):
            pass

    default_mod.DefaultDialect = DefaultDialect
    engine_mod.default = default_mod
    sa.engine = engine_mod
    monkeypatch.setitem(sys.modules, "sqlalchemy", sa)
    monkeypatch.setitem(sys.modules, "sqlalchemy.engine", engine_mod)
    monkeypatch.setitem(sys.modules, "sqlalchemy.engine.default", default_mod)

    from pinot_tpu.client import sqlalchemy_dialect as sd

    cls = sd.register_dialect()
    assert registered["pinot"] == (
        "pinot_tpu.client.sqlalchemy_dialect", "dialect")
    d = cls()
    assert cls.import_dbapi().apilevel == "2.0"
    url = types.SimpleNamespace(host="bhost", port=9001)
    args, kwargs = d.create_connect_args(url)
    assert args == ["http://bhost:9001"] and kwargs == {}

    # reflection against a real mini-cluster through the DB-API
    broker, server = _mini_cluster(tmp_path)
    try:
        from pinot_tpu.client import connect

        class FakeSAConn:  # sqlalchemy passes a wrapper with .connection
            connection = connect(broker=broker)

        assert d.get_table_names(FakeSAConn()) == ["trips"]
        assert d.has_table(FakeSAConn(), "trips")
        cols = d.get_columns(FakeSAConn(), "trips")
        assert [c["name"] for c in cols] == ["city", "fare"]
        assert [c["type"] for c in cols] == ["VARCHAR", "BIGINT"]
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# environment provider (failure domains) + segment uploader SPI
# ---------------------------------------------------------------------------


def test_failure_domain_spread(tmp_path, monkeypatch):
    """Replicas spread across DISTINCT failure domains when fd: tags are
    present (AzureEnvironmentProvider role), topping up by load only when
    domains run out."""
    from pinot_tpu.cluster.registry import ClusterRegistry, InstanceInfo, Role
    from pinot_tpu.common.environment import domain_of, failure_domain_tag
    from pinot_tpu.controller.controller import SegmentAssigner

    monkeypatch.setenv("PINOT_TPU_FAILURE_DOMAIN", "zone-a")
    assert failure_domain_tag() == "fd:zone-a"

    reg = ClusterRegistry()
    import time as _t

    now = int(_t.time() * 1000)
    for i, fd in enumerate(["a", "a", "b", "b", "c"]):
        info = InstanceInfo(f"s{i}", Role.SERVER, tags=[f"fd:{fd}"])
        reg.register_instance(info)
    assigner = SegmentAssigner(reg)
    picked = assigner.assign(3)
    domains = [domain_of(next(x for x in reg.instances() if
                              x.instance_id == p)) for p in picked]
    assert len(set(domains)) == 3, (picked, domains)
    # replication beyond distinct domains: tops up (5 servers, 3 domains)
    assert len(assigner.assign(4)) == 4


def test_segment_uploader_retries(tmp_path):
    from pinot_tpu.ingestion.uploader import create_uploader

    calls = []

    class FlakyController:
        def upload_segment(self, table, seg_dir):
            calls.append(seg_dir)
            if len(calls) < 3:
                raise OSError("deep store blip")
            return "seg_ok"

    up = create_uploader("default", FlakyController(), backoff_s=0.01)
    assert up.upload("t", "/x") == "seg_ok"
    assert len(calls) == 3

    class DeadController:
        def upload_segment(self, table, seg_dir):
            raise OSError("down")

    with pytest.raises(RuntimeError, match="after 3 attempts"):
        create_uploader("default", DeadController(),
                        backoff_s=0.01).upload("t", "/y")
