"""Device-executor tests: parity vs host path, template cache behavior.

Reference analog: InnerSegment* vs InterSegment* query suites asserting the
same results through different operator paths.
"""

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment


@pytest.fixture(scope="module")
def engines(tmp_path_factory):
    rng = np.random.default_rng(11)
    n = 4000
    cols = {
        "dim1": np.array([f"d{i:02d}" for i in range(40)])[rng.integers(0, 40, n)],
        "dim2": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
        "ivalue": rng.integers(0, 10_000, n).astype(np.int32),
        "fvalue": rng.uniform(0, 100, n).astype(np.float64),
    }
    schema = Schema.build(
        name="t",
        dimensions=[("dim1", DataType.STRING), ("dim2", DataType.STRING)],
        metrics=[("ivalue", DataType.INT), ("fvalue", DataType.DOUBLE)],
    )
    cfg = TableConfig(table_name="t", indexing=IndexingConfig())
    base = tmp_path_factory.mktemp("devseg")
    dev = QueryEngine()               # device executor auto
    host = QueryEngine(device_executor=None)
    third = n // 3
    for i, sl in enumerate([slice(0, third), slice(third, 2 * third), slice(2 * third, n)]):
        part = {k: v[sl] for k, v in cols.items()}
        build_segment(schema, part, str(base / f"s{i}"), cfg, f"s{i}")
        seg = ImmutableSegment(str(base / f"s{i}"))
        dev.add_segment("t", seg)
        host.add_segment("t", seg)
    return dev, host, cols


PARITY_QUERIES = [
    "SELECT COUNT(*) FROM t",
    "SELECT SUM(ivalue), MIN(ivalue), MAX(ivalue), AVG(ivalue) FROM t",
    "SELECT SUM(fvalue) FROM t WHERE dim2 = 'a'",
    "SELECT COUNT(*) FROM t WHERE dim1 IN ('d01','d05','d39') AND ivalue > 5000",
    "SELECT COUNT(*) FROM t WHERE dim1 LIKE 'd1%' OR dim2 != 'b'",
    "SELECT MINMAXRANGE(ivalue) FROM t WHERE ivalue BETWEEN 100 AND 9000",
    "SELECT DISTINCTCOUNT(dim1) FROM t WHERE dim2 = 'c'",
    "SELECT dim2, COUNT(*), SUM(ivalue) FROM t GROUP BY dim2 ORDER BY dim2",
    "SELECT dim1, dim2, MAX(ivalue), AVG(fvalue) FROM t GROUP BY dim1, dim2 "
    "ORDER BY dim1, dim2 LIMIT 200",
    "SELECT dim1, SUM(ivalue) FROM t WHERE ivalue + 10 < 8000 GROUP BY dim1 "
    "ORDER BY SUM(ivalue) DESC, dim1 LIMIT 15",
    "SELECT dim2, DISTINCTCOUNT(dim1) FROM t GROUP BY dim2 ORDER BY dim2",
    "SELECT dim1, COUNT(*) FROM t GROUP BY dim1 HAVING COUNT(*) > 90 "
    "ORDER BY COUNT(*) DESC, dim1 LIMIT 20",
    "SELECT SUM(ivalue) / COUNT(*) FROM t WHERE dim2 = 'b'",
    "SELECT COUNT(*) FROM t WHERE ivalue = 3",
]


def _close(a, b):
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, str) or isinstance(b, str):
        return a == b
    return np.isclose(float(a), float(b), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("sql", PARITY_QUERIES)
def test_device_host_parity(engines, sql):
    dev, host, _ = engines
    rd = dev.execute(sql)
    rh = host.execute(sql)
    assert not rd.get("exceptions"), rd
    assert not rh.get("exceptions"), rh
    rows_d = rd["resultTable"]["rows"]
    rows_h = rh["resultTable"]["rows"]
    assert len(rows_d) == len(rows_h), (rows_d[:5], rows_h[:5])
    for a, b in zip(rows_d, rows_h):
        assert all(_close(x, y) for x, y in zip(a, b)), (a, b)


def test_device_path_actually_used(engines):
    dev, _, _ = engines
    dev.execute("SELECT dim1, SUM(ivalue) FROM t GROUP BY dim1")
    assert dev.device is not None and len(dev.device._pipelines) > 0


def test_template_cache_reuse_across_literals(engines):
    dev, _, _ = engines
    dev.execute("SELECT COUNT(*) FROM t WHERE dim2 = 'a' AND ivalue > 100")
    n_templates = len(dev.device._pipelines)
    dev.execute("SELECT COUNT(*) FROM t WHERE dim2 = 'c' AND ivalue > 9000")
    assert len(dev.device._pipelines) == n_templates  # same compiled template


def test_hll_estimate_accuracy(engines):
    dev, host, cols = engines
    r = dev.execute("SELECT DISTINCTCOUNTHLL(dim1) FROM t")
    est = r["resultTable"]["rows"][0][0]
    true = len(np.unique(cols["dim1"]))
    assert abs(est - true) / true < 0.05

    # host/device registers must merge consistently (same canonical hash)
    rh = host.execute("SELECT DISTINCTCOUNTHLL(dim1) FROM t")
    assert rh["resultTable"]["rows"][0][0] == est


def test_host_fallback_for_unsupported(engines):
    dev, host, _ = engines
    # percentile is host-only; must still answer correctly
    rd = dev.execute("SELECT PERCENTILE(ivalue, 90) FROM t")
    rh = host.execute("SELECT PERCENTILE(ivalue, 90) FROM t")
    assert rd["resultTable"]["rows"] == rh["resultTable"]["rows"]


def test_large_value_sum_exact(tmp_path):
    """Regression: SUM over large int values must use the exact single-stage
    path (two-stage int32 blocks would overflow)."""
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import TableConfig

    big = np.full(600, 2**30, dtype=np.int64)
    keys = np.array(["a", "b"])[np.arange(600) % 2]
    schema = Schema.build(
        name="big", dimensions=[("k", DataType.STRING)], metrics=[("v", DataType.LONG)]
    )
    build_segment(schema, {"k": keys, "v": big}, str(tmp_path / "s0"),
                  TableConfig(table_name="big"), "s0")
    eng = QueryEngine()
    eng.add_segment("big", ImmutableSegment(str(tmp_path / "s0")))
    r = eng.execute("SELECT k, SUM(v) FROM big GROUP BY k ORDER BY k")
    assert len(eng.device._pipelines) > 0  # device path taken
    assert r["resultTable"]["rows"] == [["a", 300 * 2**30], ["b", 300 * 2**30]], r


@pytest.fixture(scope="module")
def mm_engine(engines, tmp_path_factory):
    """Device engine with the factored matmul group-by kernel forced on
    (Pallas interpret mode on the CPU test mesh)."""
    from pinot_tpu.engine.device import DeviceExecutor

    dev, _, _ = engines
    eng = QueryEngine(device_executor=DeviceExecutor(mm_mode="interpret"))
    for seg in dev.tables["t"].segments.values():
        eng.add_segment("t", seg)
    return eng


MM_QUERIES = [
    "SELECT dim2, COUNT(*), SUM(ivalue) FROM t GROUP BY dim2 ORDER BY dim2",
    "SELECT dim2, DISTINCTCOUNTHLL(dim1) FROM t GROUP BY dim2 ORDER BY dim2",
    "SELECT DISTINCTCOUNTHLL(dim1) FROM t",
    "SELECT dim1, dim2, COUNT(*), AVG(fvalue) FROM t GROUP BY dim1, dim2 "
    "ORDER BY dim1, dim2 LIMIT 200",
    "SELECT dim1, SUM(ivalue), SUM(fvalue), MAX(ivalue) FROM t "
    "WHERE dim2 != 'b' GROUP BY dim1 ORDER BY dim1 LIMIT 50",
]


@pytest.mark.parametrize("sql", MM_QUERIES)
def test_matmul_groupby_parity(mm_engine, engines, sql):
    """The factored one-hot matmul kernel must agree with the host path
    (exact ints, float sums to f32-level tolerance)."""
    _, host, _ = engines
    rd = mm_engine.execute(sql)
    rh = host.execute(sql)
    assert not rd.get("exceptions"), rd
    rows_d, rows_h = rd["resultTable"]["rows"], rh["resultTable"]["rows"]
    assert len(rows_d) == len(rows_h)
    for a, b in zip(rows_d, rows_h):
        assert all(_close(x, y) for x, y in zip(a, b)), (a, b)


class TestSortedHighCardGroupBy:
    """Radix-partitioned high-cardinality device regime (MAP_BASED
    analog): the cartesian dict-id product exceeds MAX_DENSE_GROUPS, so
    the packed keys ride ops/radix_groupby.py (chunk-local sorts +
    run-end partials + compacted merge) into a capped table."""

    @pytest.fixture(scope="class")
    def hc(self, tmp_path_factory):
        rng = np.random.default_rng(23)
        n = 30_000
        # 5000 users x 4096 items >> 4M dense cap; ~25k distinct pairs
        cols = {
            "user": np.array([f"u{i:04d}" for i in range(5000)])[
                rng.integers(0, 5000, n)],
            "item": np.array([f"i{i:04d}" for i in range(4096)])[
                rng.integers(0, 4096, n)],
            "spend": rng.integers(1, 500, n).astype(np.int64),
        }
        schema = Schema.build(
            name="hc",
            dimensions=[("user", DataType.STRING), ("item", DataType.STRING)],
            metrics=[("spend", DataType.LONG)],
        )
        cfg = TableConfig(table_name="hc")
        base = tmp_path_factory.mktemp("hcseg")
        dev = QueryEngine()
        host = QueryEngine(device_executor=None)
        half = n // 2
        for i, sl in enumerate([slice(0, half), slice(half, n)]):
            part = {k: v[sl] for k, v in cols.items()}
            build_segment(schema, part, str(base / f"s{i}"), cfg, f"s{i}")
            seg = ImmutableSegment(str(base / f"s{i}"))
            dev.add_segment("hc", seg)
            host.add_segment("hc", seg)
        return dev, host, cols

    @pytest.mark.parametrize("sql", [
        "SELECT user, item, SUM(spend), COUNT(*) FROM hc "
        "GROUP BY user, item ORDER BY SUM(spend) DESC, user, item LIMIT 25",
        "SELECT user, item, MIN(spend), MAX(spend), AVG(spend) FROM hc "
        "WHERE spend > 100 GROUP BY user, item "
        "ORDER BY MAX(spend) DESC, user, item LIMIT 40",
        "SELECT user, MINMAXRANGE(spend) FROM hc GROUP BY user "
        "ORDER BY user LIMIT 30",
    ])
    def test_parity_with_host(self, hc, sql):
        dev, host, _ = hc
        rd, rh = dev.execute(sql), host.execute(sql)
        assert not rd.get("exceptions"), rd
        assert not rh.get("exceptions"), rh
        assert rd["resultTable"]["rows"] == rh["resultTable"]["rows"], sql

    def test_sorted_template_used(self, hc):
        dev, _, _ = hc
        dev.execute("SELECT user, item, SUM(spend) FROM hc GROUP BY user, item")
        shapes = {t[0] for (t, _m, _bs, _w, _tr, _pl) in dev.device._pipelines}
        assert "groupby_sorted" in shapes

    def test_unsupported_agg_falls_back_to_host(self, hc):
        dev, host, _ = hc
        sql = ("SELECT user, item, DISTINCTCOUNT(item) FROM hc "
               "GROUP BY user, item ORDER BY user, item LIMIT 10")
        rd, rh = dev.execute(sql), host.execute(sql)
        assert rd["resultTable"]["rows"] == rh["resultTable"]["rows"]

    def test_group_table_overflow_falls_back_to_host(self, hc):
        """More distinct groups than the cap: the device result would be
        key-order-truncated, so it must defer to the host path (r3
        review)."""
        dev_small = QueryEngine(num_groups_limit=1000)
        host_small = QueryEngine(device_executor=None, num_groups_limit=1000)
        src, _, _ = hc
        for seg in src.tables["hc"].segments.values():
            dev_small.add_segment("hc", seg)
            host_small.add_segment("hc", seg)
        sql = ("SELECT user, item, SUM(spend) FROM hc GROUP BY user, item "
               "ORDER BY user, item LIMIT 20")
        rd, rh = dev_small.execute(sql), host_small.execute(sql)
        assert rd["resultTable"]["rows"] == rh["resultTable"]["rows"]

    def test_float_sums_no_cancellation(self, tmp_path):
        """Float SUMs use the order-independent scatter, not a global
        cumsum difference — a tiny group next to huge ones must not lose
        its value to cancellation (r3 review)."""
        n = 20_000
        rng = np.random.default_rng(9)
        vals = rng.uniform(1e9, 1e10, n)
        # one tiny-magnitude group buried at a random key position
        cols = {
            # dtype wide enough for the injected key: assigning "a_tiny"
            # into a '<U4' array would silently truncate to "a_ti"
            "a": np.array([f"a{i:03d}" for i in range(300)],
                          dtype="<U8")[rng.integers(0, 300, n)],
            "b": np.array([f"b{i:05d}" for i in range(n)]),
            "v": vals,
        }
        cols["a"][:3] = "a_tiny"
        cols["b"][:3] = np.array(["b_t0", "b_t1", "b_t2"])
        cols["v"][:3] = [1.25, 2.5, 1.25]
        schema = Schema.build(
            name="fs",
            dimensions=[("a", DataType.STRING), ("b", DataType.STRING)],
            metrics=[("v", DataType.DOUBLE)],
        )
        build_segment(schema, cols, str(tmp_path / "s0"),
                      TableConfig(table_name="fs"), "s0")
        seg = ImmutableSegment(str(tmp_path / "s0"))
        dev = QueryEngine()
        dev.add_segment("fs", seg)
        r = dev.execute("SELECT a, b, SUM(v) FROM fs WHERE a = 'a_tiny' "
                        "GROUP BY a, b ORDER BY b")
        shapes = {t[0] for (t, _m, _bs, _w, _tr, _pl) in dev.device._pipelines}
        assert "groupby_sorted" in shapes
        got = [row[2] for row in r["resultTable"]["rows"]]
        assert got == [1.25, 2.5, 1.25], got

    def test_large_int_sums_exact(self, tmp_path):
        """Integer payloads accumulate in int64 on the sorted path — per-doc
        f64 adds would round past 2^53 (r3 review)."""
        rng = np.random.default_rng(4)
        n = 20_000
        big = (rng.integers(1, 1 << 40, n) << 14).astype(np.int64)
        cols = {
            # every row a distinct b: global cards 300 x 20000 = 6M > dense
            # cap, while the ~20k real groups fit the sorted table
            "a": np.array([f"a{i:03d}" for i in range(300)])[
                rng.integers(0, 300, n)],
            "b": np.array([f"b{i:05d}" for i in range(n)]),
            "v": big,
        }
        schema = Schema.build(
            name="bigs",
            dimensions=[("a", DataType.STRING), ("b", DataType.STRING)],
            metrics=[("v", DataType.LONG)],
        )
        build_segment(schema, cols, str(tmp_path / "s0"),
                      TableConfig(table_name="bigs"), "s0")
        seg = ImmutableSegment(str(tmp_path / "s0"))
        dev = QueryEngine()
        host = QueryEngine(device_executor=None)
        dev.add_segment("bigs", seg)
        host.add_segment("bigs", seg)
        sql = ("SELECT a, b, SUM(v) FROM bigs GROUP BY a, b "
               "ORDER BY SUM(v) DESC, a, b LIMIT 50")
        rd, rh = dev.execute(sql), host.execute(sql)
        shapes = {t[0] for (t, _m, _bs, _w, _tr, _pl) in dev.device._pipelines}
        assert "groupby_sorted" in shapes
        assert rd["resultTable"]["rows"] == rh["resultTable"]["rows"]


class TestDeviceDistinct:
    """SELECT DISTINCT executes as group-keys-only on the device
    (DistinctAggregationFunction analog)."""

    def test_distinct_parity_and_device_used(self, engines):
        dev, host, _ = engines
        for sql in (
            "SELECT DISTINCT dim2 FROM t ORDER BY dim2",
            "SELECT DISTINCT dim1, dim2 FROM t ORDER BY dim1, dim2 LIMIT 500",
            "SELECT DISTINCT dim1 FROM t WHERE ivalue > 9000 ORDER BY dim1",
        ):
            rd, rh = dev.execute(sql), host.execute(sql)
            assert not rd.get("exceptions"), rd
            assert rd["resultTable"]["rows"] == rh["resultTable"]["rows"], sql
        shapes = {t[0] for (t, _m, _bs, _w, _tr, _pl) in dev.device._pipelines}
        assert "groupby" in shapes

    def test_distinct_expression_falls_back(self, engines):
        dev, host, _ = engines
        sql = "SELECT DISTINCT ivalue + 1 FROM t ORDER BY ivalue + 1 LIMIT 5"
        rd, rh = dev.execute(sql), host.execute(sql)
        assert rd["resultTable"]["rows"] == rh["resultTable"]["rows"]


class TestSortedProjection:
    def test_cached_projection_matches_cold_and_host(self, tmp_path):
        """The lazily-built sorted (group, hash) projection answers
        filterless terminal HLL scans bit-identically to the in-query-sort
        and host paths, and is actually CACHED on the batch."""
        import numpy as np

        from pinot_tpu.common.datatypes import DataType
        from pinot_tpu.common.schema import Schema
        from pinot_tpu.engine.engine import QueryEngine
        from pinot_tpu.storage.creator import build_segment

        rng = np.random.default_rng(13)
        n = 60_000
        # u must be a DIMENSION (dict-encoded): the device HLL path
        # prehashes dictionary values
        schema = Schema.build(
            name="sp", dimensions=[("g", DataType.INT), ("u", DataType.LONG)],
            metrics=[("v", DataType.INT)])
        segs = []
        for i in range(2):
            cols = {
                # global card high enough that G*m exceeds the mm register
                # kernel's bound -> the sorted paths engage (log2m=10)
                "g": rng.integers(0, 3000, n).astype(np.int32),
                "u": rng.integers(0, 500_000, n).astype(np.int64),
                "v": rng.integers(0, 9, n).astype(np.int32),
            }
            segs.append(build_segment(
                schema, cols, str(tmp_path / f"s{i}"), segment_name=f"s{i}"))
        sql = ("SET useStarTree = false; "
               "SELECT g, COUNT(*), DISTINCTCOUNTHLL(u) FROM sp "
               "GROUP BY g ORDER BY COUNT(*) DESC, g LIMIT 20")
        cold_sql = sql.replace("SET useStarTree = false; ",
                               "SET useStarTree = false; "
                               "SET useSortedProjection = false; ")
        from pinot_tpu.engine.device import DeviceExecutor

        eng = QueryEngine(device_executor=DeviceExecutor(mm_mode="interpret"))
        for s in segs:
            eng.add_segment("sp", s)
        warm = eng.execute(sql)
        assert not warm.get("exceptions"), warm
        # the projection is resident on the batch after the first execute
        ctx = next(iter(eng.device._batches.values()))
        assert ctx._sorted_hll, "sorted projection was not cached"
        again = eng.execute(sql)
        cold = eng.execute(cold_sql)
        host_eng = QueryEngine(device_executor=None)
        for s in segs:
            host_eng.add_segment("sp", s)
        host = host_eng.execute(sql)
        rows = warm["resultTable"]["rows"]
        assert rows == again["resultTable"]["rows"]
        assert rows == cold["resultTable"]["rows"]
        assert rows == host["resultTable"]["rows"]


class TestSortedRegimeBoundaries:
    """Satellite for the radix tentpole: drive group counts across the
    sorted_k = min(numGroupsLimit, MAX_SORTED_GROUPS) table-cap and the
    host-overflow boundaries, asserting device == host on every side and
    numGroupsLimitReached semantics on both paths. The fixture pins BOTH
    column dictionaries at full cardinality (3000 x 1500 = 4.5M key space
    > MAX_DENSE_GROUPS) with EXACTLY 5000 distinct pairs, so each engine
    limit below/above 5000 picks the regime deterministically."""

    U, I, D, N = 3000, 1500, 5000, 40_000

    @pytest.fixture(scope="class")
    def bc(self, tmp_path_factory):
        rng = np.random.default_rng(31)
        U, I, D, n = self.U, self.I, self.D, self.N
        base = sorted({j * I + (j % I) for j in range(U)}  # covers every u
                      | set(range(I)))                     # covers every i
        pool = rng.choice(U * I, size=2 * D, replace=False)
        bset = set(base)
        extra = [int(p) for p in pool if p not in bset][:D - len(base)]
        pids = np.array(base + extra)
        assert len(pids) == D
        draw = np.concatenate([pids, rng.choice(pids, n - D)])
        rng.shuffle(draw)
        cols = {
            "u": (draw // I).astype(np.int32),
            "i": (draw % I).astype(np.int32),
            "v": rng.integers(-1000, 1000, n).astype(np.int64),
            "f": np.round(rng.uniform(-5, 5, n), 6),
        }
        schema = Schema.build(
            name="bc",
            dimensions=[("u", DataType.INT), ("i", DataType.INT)],
            metrics=[("v", DataType.LONG), ("f", DataType.DOUBLE)],
        )
        base_dir = tmp_path_factory.mktemp("bcseg")
        segs = []
        quarter = n // 4
        for s in range(4):
            part = {k: v[s * quarter:(s + 1) * quarter]
                    for k, v in cols.items()}
            build_segment(schema, part, str(base_dir / f"s{s}"),
                          TableConfig(table_name="bc"), f"s{s}")
            segs.append(ImmutableSegment(str(base_dir / f"s{s}")))
        return segs

    SQL = ("SELECT u, i, COUNT(*), SUM(v), AVG(v), MIN(v), MAX(v), "
           "MINMAXRANGE(v), SUM(f) FROM bc GROUP BY u, i "
           "ORDER BY SUM(v) DESC, u, i LIMIT 30")

    def _engines(self, segs, limit):
        dev = QueryEngine(num_groups_limit=limit)
        host = QueryEngine(device_executor=None, num_groups_limit=limit)
        for s in segs:
            dev.add_segment("bc", s)
            host.add_segment("bc", s)
        return dev, host

    def _assert_parity(self, dev, host, sql=None):
        rd, rh = dev.execute(sql or self.SQL), host.execute(sql or self.SQL)
        assert not rd.get("exceptions"), rd
        assert not rh.get("exceptions"), rh
        rows_d, rows_h = rd["resultTable"]["rows"], rh["resultTable"]["rows"]
        assert len(rows_d) == len(rows_h)
        for a, b in zip(rows_d, rows_h):
            assert all(_close(x, y) for x, y in zip(a, b)), (a, b)
        return rd, rh

    def test_below_cap_device_radix_regime(self, bc):
        """D < sorted_k: the radix regime answers on device, exactly."""
        dev, host = self._engines(bc, limit=6000)
        rd, rh = self._assert_parity(dev, host)
        shapes = {t[0] for (t, _m, _bs, _w, _tr, _pl) in dev.device._pipelines}
        assert "groupby_sorted" in shapes
        assert rd["numGroupsLimitReached"] is False
        assert rh["numGroupsLimitReached"] is False

    def test_above_cap_host_overflow_fallback(self, bc):
        """D > sorted_k: the device table would truncate, so the executor
        must detect overflow and defer to the host path (both engines
        then flag the limit and answer identically)."""
        dev, host = self._engines(bc, limit=4000)
        rd, rh = self._assert_parity(dev, host)
        assert rd["numGroupsLimitReached"] is True
        assert rh["numGroupsLimitReached"] is True

    def test_max_sorted_groups_ceiling(self, bc, monkeypatch):
        """sorted_k is min(numGroupsLimit, MAX_SORTED_GROUPS): with the
        hard ceiling lowered below D, even a generous numGroupsLimit must
        route through the host fallback — and raising it back re-enables
        the device regime."""
        from pinot_tpu.engine import device as devmod

        monkeypatch.setattr(devmod, "MAX_SORTED_GROUPS", 4500)
        dev, host = self._engines(bc, limit=100_000)
        self._assert_parity(dev, host)
        monkeypatch.setattr(devmod, "MAX_SORTED_GROUPS", 1 << 17)
        dev2, host2 = self._engines(bc, limit=100_000)
        rd, _rh = self._assert_parity(dev2, host2)
        shapes = {t[0] for (t, _m, _bs, _w, _tr, _pl) in dev2.device._pipelines}
        assert "groupby_sorted" in shapes
        assert rd["numGroupsLimitReached"] is False

    def test_set_num_groups_limit_flags_both_paths(self, bc):
        """Per-query SET numGroupsLimit below D: results are plan-
        dependent-partial by reference contract — BOTH paths must say so
        (rows are not compared; the flag is the contract)."""
        dev, host = self._engines(bc, limit=6000)
        sql = ("SET numGroupsLimit = 1000; "
               "SELECT u, i, COUNT(*) FROM bc GROUP BY u, i "
               "ORDER BY COUNT(*) DESC LIMIT 5")
        for eng in (dev, host):
            r = eng.execute(sql)
            assert not r.get("exceptions"), r
            assert r["numGroupsLimitReached"] is True, r

    def test_chunked_plan_parity(self, bc, monkeypatch):
        """Force the multi-chunk radix plan at engine scale (CHUNK_ROWS
        shrunk + compaction ratio tightened so the 40k-row batch splits
        into level-1 chunks + a merge level) — results must not depend on
        the chunk plan."""
        from pinot_tpu.ops import radix_groupby as radix

        orig_plan = radix.plan_chunks
        monkeypatch.setattr(radix, "CHUNK_ROWS", 256)
        monkeypatch.setattr(
            radix, "plan_chunks",
            lambda n, k, chunk_rows=None, min_ratio=None:
            orig_plan(n, k, chunk_rows, radix.HLL_COMPACT_RATIO))
        C, _L = radix.plan_chunks(self.N, 6000)
        assert C > 1, "plan must actually chunk at this scale"
        dev, host = self._engines(bc, limit=6000)
        self._assert_parity(dev, host)

    def test_unsupported_agg_family_falls_back(self, bc):
        """DISTINCTCOUNTHLL is not in SORTED_AGGS: the sorted regime must
        defer to the host rather than mis-aggregate."""
        dev, host = self._engines(bc, limit=6000)
        sql = ("SELECT u, i, DISTINCTCOUNTHLL(v) FROM bc GROUP BY u, i "
               "ORDER BY u, i LIMIT 10")
        self._assert_parity(dev, host, sql)
