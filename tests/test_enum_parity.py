"""Registry diffs against the reference's function enums.

Asserts the ONLY missing names are the deliberate, documented exclusions
(PARITY.md): GROOVY/SCALAR (JVM escape hatches with no analog here) and
names that are covered structurally rather than as registry entries
(filter predicates, the DISTINCT query shape).
"""

import glob
import os
import re

import pytest

REF = "/root/reference"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference tree not present")

# covered by the engine structurally, not by a transform-registry entry
TRANSFORM_STRUCTURAL = {
    "IN": "filter predicate (query/context.py PredicateType.IN)",
    "IS_NULL": "filter predicate (PredicateType.IS_NULL)",
    "IS_NOT_NULL": "filter predicate (PredicateType.IS_NOT_NULL)",
}
TRANSFORM_EXCLUDED = {
    "GROOVY": "JVM script escape hatch — no analog by design (PARITY.md)",
    "SCALAR": "JVM @ScalarFunction reflection wrapper — registry IS the analog",
}
AGG_STRUCTURAL = {
    "DISTINCT": "query shape (SELECT DISTINCT), not an aggregation spec",
}


def _transform_enum():
    src = open(os.path.join(
        REF, "pinot-common/src/main/java/org/apache/pinot/common/function/"
             "TransformFunctionType.java")).read()
    return re.findall(r'^\s*([A-Z_0-9]+)\(((?:"[^"]*"(?:,\s*)?)+)\)', src,
                      re.M)


def test_transform_registry_covers_reference_enum():
    from pinot_tpu.ops.transform import REGISTRY

    missing = []
    for enum, argstr in _transform_enum():
        if enum in TRANSFORM_STRUCTURAL or enum in TRANSFORM_EXCLUDED:
            continue
        aliases = re.findall(r'"([^"]+)"', argstr)
        keys = set()
        for a in aliases + [enum]:
            keys.add(a.lower())
            keys.add(a.lower().replace("_", ""))
        if not any(k in REGISTRY for k in keys):
            missing.append(enum)
    assert not missing, f"transform enum gaps: {missing}"


def test_transform_exclusions_are_exact():
    """The structural/excluded sets must not rot: every name in them still
    exists in the reference enum, and none of them is (newly) registered."""
    enums = {e for e, _ in _transform_enum()}
    for name in list(TRANSFORM_STRUCTURAL) + list(TRANSFORM_EXCLUDED):
        assert name in enums, f"{name} no longer in reference enum"
    from pinot_tpu.ops.transform import REGISTRY

    for name in TRANSFORM_EXCLUDED:
        assert name.lower() not in REGISTRY


def test_aggregation_registry_covers_reference_enum():
    from pinot_tpu.engine.aggspec import _SPECS

    hits = glob.glob(os.path.join(
        REF, "pinot-segment-spi/**/AggregationFunctionType.java"),
        recursive=True)
    assert hits
    src = open(hits[0]).read()
    names = re.findall(r'^\s*([A-Z_0-9]+)\("([^"]+)"\)', src, re.M)
    assert len(names) >= 40  # the enum parse itself must not silently rot
    missing = [
        e for e, n in names
        if e not in AGG_STRUCTURAL
        and n.lower() not in _SPECS and e.lower() not in _SPECS
    ]
    assert not missing, f"aggregation enum gaps: {missing}"


def test_parity_doc_mentions_exclusions():
    doc = open("/root/repo/PARITY.md").read().upper()
    for name in ("GROOVY",):
        assert name in doc, f"PARITY.md must document the {name} exclusion"
