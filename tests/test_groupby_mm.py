"""Unit tests for the factored one-hot matmul group-by kernel
(ops/groupby_mm.py), run in Pallas interpret mode on the CPU test mesh.

Oracle: numpy bincount. Covers int planes with offsets (negatives, wide
ranges), exact float split, the overflow slot, and non-aligned row counts.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pinot_tpu.ops import groupby_mm as mm


def _run(gid_np, channels_np, num_groups):
    out = mm.group_sums(
        jnp.asarray(gid_np),
        jnp.asarray(channels_np, dtype=jnp.bfloat16),
        num_groups,
        interpret=True,
    )
    return np.asarray(jax.device_get(out))


class TestKernel:
    def test_count_and_plane_sums(self):
        rng = np.random.default_rng(1)
        n, g = 3000, 517
        gid = rng.integers(0, g, n).astype(np.int32)
        vals = rng.integers(0, 256, n).astype(np.int32)
        ch = np.stack([np.ones(n), vals]).astype(np.float32)
        out = _run(gid, ch, g)
        assert np.array_equal(out[0], np.bincount(gid, minlength=g))
        assert np.array_equal(
            out[1], np.bincount(gid, weights=vals.astype(np.float64), minlength=g)
        )

    def test_overflow_slot_dropped(self):
        gid = np.array([0, 1, 5, 5, 2], dtype=np.int32)  # 5 == overflow for g=5
        ch = np.ones((1, 5), dtype=np.float32)
        out = _run(gid, ch, 5)
        assert out.shape == (1, 5)
        assert np.array_equal(out[0], [1, 1, 1, 0, 0])

    def test_small_g(self):
        rng = np.random.default_rng(2)
        gid = rng.integers(0, 3, 500).astype(np.int32)
        ch = np.ones((1, 500), dtype=np.float32)
        out = _run(gid, ch, 3)
        assert np.array_equal(out[0], np.bincount(gid, minlength=3))


class TestPlanes:
    def test_int_planes_roundtrip_negative_and_wide(self):
        rng = np.random.default_rng(3)
        n, g = 2000, 37
        gid_np = rng.integers(0, g, n).astype(np.int32)
        lo, hi = -(2**33), 2**33
        vals = rng.integers(lo, hi, n).astype(np.int64)
        nplanes = mm.int_planes_needed(lo, hi)
        assert nplanes == 5  # range 2^34 → 5 byte planes

        planes = mm.int_planes(jnp.asarray(vals), jnp.int64(lo), nplanes)
        ch = jnp.stack([jnp.ones(n, jnp.bfloat16)] + planes)
        out = mm.group_sums(jnp.asarray(gid_np), ch, g, interpret=True)
        count = jnp.asarray(np.round(np.asarray(out[0])).astype(np.int64))
        total = mm.recombine_int(list(out[1:]), count, jnp.int64(lo))
        want = np.zeros(g, dtype=np.int64)
        np.add.at(want, gid_np, vals)
        assert np.array_equal(np.asarray(total), want)

    def test_float_planes_exact(self):
        rng = np.random.default_rng(4)
        n, g = 4000, 11
        gid_np = rng.integers(0, g, n).astype(np.int32)
        vals = rng.uniform(-50, 50, n).astype(np.float32)
        planes = mm.float_planes(jnp.asarray(vals))
        ch = jnp.stack(planes)
        out = mm.group_sums(jnp.asarray(gid_np), ch, g, interpret=True)
        got = np.asarray(mm.recombine_float(list(out)))
        want = np.bincount(gid_np, weights=vals.astype(np.float64), minlength=g)
        assert np.abs(got - want).max() <= 1e-6 * max(1.0, np.abs(want).max())

    def test_planes_needed(self):
        assert mm.int_planes_needed(0, 255) == 1
        assert mm.int_planes_needed(0, 256) == 2
        assert mm.int_planes_needed(-100, 100) == 1
        assert mm.int_planes_needed(0, 2**16) == 3
        assert mm.int_planes_needed(0, 2**31 - 1) == 4

    def test_mm_supported_guard(self):
        assert mm.mm_supported(6240, 6)
        assert not mm.mm_supported(4_000_000, 6)  # acc would blow VMEM
