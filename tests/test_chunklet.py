"""Chunklet subsystem correctness: columnar batch ingest equivalence and
device-promotion differentials.

The two contracts the subsystem must never bend (realtime/chunklet.py):

1. ``index_batch`` is byte-for-byte EQUIVALENT to row-at-a-time ``index``
   — same query results while consuming AND after seal (the seal-
   equivalence tests);
2. splitting a consuming segment into device chunklets + host tail changes
   WHERE rows execute, never WHAT they answer: device+host mixed results
   == all-host == post-seal immutable, including under upsert validDocIds
   masks (the differential tests).
"""

import threading
import time

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import (
    ChunkletConfig,
    TableConfig,
    UpsertConfig,
)
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.realtime.chunklet import split_for_query
from pinot_tpu.realtime.upsert import PartitionUpsertMetadataManager
from pinot_tpu.storage.mutable import MutableSegment


def make_schema(pk=False, mv=False):
    return Schema.build(
        name="rt",
        dimensions=[("zone", DataType.STRING), ("hour", DataType.INT)],
        multi_value_dimensions=[("tags", DataType.STRING)] if mv else [],
        metrics=[("fare", DataType.INT)],
        datetimes=[("ts", DataType.LONG)],
        primary_key_columns=["zone"] if pk else [],
    )


def make_rows(n, zones=40, seed=0, with_nulls=True):
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        r = {
            "zone": f"z{int(rng.integers(0, zones)):03d}",
            "hour": int(rng.integers(0, 24)),
            "fare": int(rng.integers(0, 10_000)),
            "ts": i,
        }
        if with_nulls and i % 37 == 0:
            del r["fare"]  # -> null default + null vector entry
        rows.append(r)
    return rows


def chunklet_config(rows_per=1024, min_rows=0):
    return TableConfig(
        table_name="rt",
        chunklets=ChunkletConfig(enabled=True, rows_per_chunklet=rows_per,
                                 device_min_rows=min_rows))


QUERIES = [
    "SELECT COUNT(*), SUM(fare) FROM rt",
    "SELECT zone, COUNT(*), SUM(fare), MIN(fare), MAX(fare) FROM rt "
    "GROUP BY zone ORDER BY zone LIMIT 100",
    "SELECT hour, AVG(fare) FROM rt WHERE zone <> 'z001' "
    "GROUP BY hour ORDER BY hour LIMIT 30",
    "SELECT COUNT(*) FROM rt WHERE fare IS NULL",
    "SELECT COUNT(*) FROM rt WHERE fare > 5000 AND hour BETWEEN 3 AND 20",
]


def rows_of(engine, sql):
    r = engine.execute(sql)
    assert not r.get("exceptions"), (sql, r)
    return r["resultTable"]["rows"]


class TestIndexBatchEquivalence:
    def test_seal_equivalence_batch_vs_rows(self, tmp_path):
        rows = make_rows(3000)
        a = MutableSegment(make_schema(), "a", chunklet_config())
        a.index_batch(rows)
        b = MutableSegment(make_schema(), "b")
        for r in rows:
            b.index(r)
        assert a.n_docs == b.n_docs == 3000
        ea = QueryEngine(device_executor=None)
        ea.table("rt").add_segment(a)
        eb = QueryEngine(device_executor=None)
        eb.table("rt").add_segment(b)
        for sql in QUERIES:
            assert rows_of(ea, sql) == rows_of(eb, sql), sql
        # sealed outputs answer identically too (chunklet seal-reuse path
        # on one side: a has promoted blocks, b never had any)
        a.chunklet_index.promote()
        assert len(a.chunklet_index.chunklets) > 0
        sa = a.seal(str(tmp_path / "sa"))
        sb = b.seal(str(tmp_path / "sb"))
        e1 = QueryEngine(device_executor=None)
        e1.table("rt").add_segment(sa)
        e2 = QueryEngine(device_executor=None)
        e2.table("rt").add_segment(sb)
        for sql in QUERIES:
            assert rows_of(e1, sql) == rows_of(e2, sql), sql

    def test_mv_and_missing_columns(self):
        schema = make_schema(mv=True)
        rows = [
            {"zone": "a", "hour": 1, "fare": 10, "ts": 0,
             "tags": ["x", "y"]},
            {"zone": "b", "hour": 2, "ts": 1, "tags": []},  # fare null
            {"zone": "a", "hour": 3, "fare": 30, "ts": 2, "tags": ["y"]},
        ]
        a = MutableSegment(schema, "a")
        a.index_batch(rows)
        b = MutableSegment(schema, "b")
        for r in rows:
            b.index(r)
        # MV schema: no chunklet index (host path keeps the whole segment)
        assert a.chunklet_index is None
        for seg in (a, b):
            e = QueryEngine(device_executor=None)
            e.table("rt").add_segment(seg)
            assert rows_of(e, "SELECT COUNT(*) FROM rt WHERE tags = 'y'") \
                == [[2]]
            assert rows_of(e, "SELECT COUNT(*) FROM rt WHERE fare IS NULL") \
                == [[1]]

    def test_bad_row_fails_batch_atomically(self):
        seg = MutableSegment(make_schema(), "a")
        with pytest.raises(Exception):
            seg.index_batch([
                {"zone": "a", "hour": 1, "fare": 1, "ts": 0},
                {"zone": "b", "hour": "not-an-int", "fare": 2, "ts": 1},
            ])
        assert seg.n_docs == 0  # nothing published
        # and state is not corrupted for subsequent appends
        seg.index_batch([{"zone": "c", "hour": 3, "fare": 3, "ts": 2}])
        assert seg.n_docs == 1
        assert seg.row_value("zone", 0) == "c"

    def test_upsert_keeps_row_path_semantics(self):
        # index_batch is not used for upsert tables by the manager; the
        # segment-level API still grows validDocIds correctly if called
        seg = MutableSegment(make_schema(pk=True), "a",
                             chunklet_config(), enable_upsert=True)
        seg.index_batch(make_rows(5000, with_nulls=False))
        assert seg.valid_docs(5000).all()


class TestChunkletPromotion:
    def test_promotion_boundaries(self):
        seg = MutableSegment(make_schema(), "a", chunklet_config(1024))
        ci = seg.chunklet_index
        seg.index_batch(make_rows(1023))
        assert ci.promote() == 0  # one short of a block
        seg.index_batch(make_rows(1))
        assert ci.promote() == 1
        assert ci.frozen_docs == 1024
        seg.index_batch(make_rows(5000))
        assert ci.promote() == 4
        assert ci.chunklets[-1].stop == 5120
        # chunklet metadata matches its slice
        ck = ci.chunklets[0]
        assert ck.n_docs == 1024
        assert ck.column_metadata("zone").cardinality > 0
        np.testing.assert_array_equal(
            ck.flat_values("fare"),
            np.asarray(seg._cols["fare"].values(1024)))

    def test_crossover_threshold_gates_split(self):
        seg = MutableSegment(make_schema(), "a",
                             chunklet_config(1024, min_rows=10_000))
        seg.index_batch(make_rows(4096, with_nulls=False))
        seg.chunklet_index.promote()
        assert split_for_query(seg) is None  # frozen 4096 < 10_000
        seg.index_batch(make_rows(8000, with_nulls=False))
        seg.chunklet_index.promote()
        split = split_for_query(seg)
        assert split is not None
        device, host = split
        assert sum(c.n_docs for c in device) == 11 * 1024
        assert sum(h.n_docs for h in host) == seg.n_docs - 11 * 1024


class TestMixedBackendDifferential:
    """device-chunklet + host-tail == all-host == post-seal immutable."""

    def _twins(self, rows):
        a = MutableSegment(make_schema(), "a", chunklet_config())
        a.index_batch(rows)
        a.chunklet_index.promote()
        assert len(a.chunklet_index.chunklets) >= 2
        b = MutableSegment(make_schema(), "b")
        for r in rows:
            b.index(r)
        dev = QueryEngine()
        dev.table("rt").add_segment(a)
        host = QueryEngine(device_executor=None)
        host.table("rt").add_segment(b)
        return a, dev, host

    def test_differential_consuming_vs_host_vs_sealed(self, tmp_path):
        rows = make_rows(5500)
        a, dev, host = self._twins(rows)
        # the split actually engages (device chunklets exist)
        assert split_for_query(a) is not None
        for sql in QUERIES:
            assert rows_of(dev, sql) == rows_of(host, sql), sql
        sealed = a.seal(str(tmp_path / "s"))
        es = QueryEngine()
        es.table("rt").add_segment(sealed)
        for sql in QUERIES:
            assert rows_of(es, sql) == rows_of(host, sql), sql

    def test_differential_under_upsert_masks(self):
        schema = make_schema(pk=True)
        cfg = TableConfig(
            table_name="rt",
            upsert=UpsertConfig(mode="FULL", comparison_column="ts"),
            chunklets=ChunkletConfig(enabled=True, rows_per_chunklet=1024,
                                     device_min_rows=0))
        rng = np.random.default_rng(9)
        n = 4000
        rows = [{"zone": f"z{int(rng.integers(0, 2500)):04d}",
                 "hour": int(rng.integers(0, 24)),
                 "fare": int(rng.integers(0, 1000)), "ts": i}
                for i, _ in enumerate(range(n))]

        def build(table_config, with_chunklets):
            seg = MutableSegment(schema, "s", table_config,
                                 enable_upsert=True)
            ups = PartitionUpsertMetadataManager("ts")
            for r in rows:
                did = seg.index(r)
                ups.add_record(seg, did, (r["zone"],), r["ts"])
            if with_chunklets:
                seg.chunklet_index.promote()
            # late updates: invalidations land INSIDE the frozen prefix
            for i in range(600):
                r = {"zone": f"z{i % 2500:04d}", "hour": 0,
                     "fare": 99_999, "ts": n + i}
                did = seg.index(r)
                ups.add_record(seg, did, (r["zone"],), r["ts"])
            if with_chunklets:
                seg.chunklet_index.promote()
            return seg

        a = build(cfg, True)
        dirty = sum(0 if c.is_clean else 1
                    for c in a.chunklet_index.chunklets)
        assert dirty > 0  # masks actually engaged over the prefix
        b = build(TableConfig(table_name="rt", upsert=cfg.upsert), False)
        dev = QueryEngine()
        dev.table("rt").add_segment(a)
        host = QueryEngine(device_executor=None)
        host.table("rt").add_segment(b)
        for sql in QUERIES[:3] + [
                "SELECT COUNT(*) FROM rt WHERE fare = 99999"]:
            assert rows_of(dev, sql) == rows_of(host, sql), sql

    def test_differential_while_ingesting(self):
        """Snapshot consistency: queries during concurrent batch ingest +
        promotion never error and counts only grow."""
        seg = MutableSegment(make_schema(), "a", chunklet_config())
        eng = QueryEngine()
        eng.table("rt").add_segment(seg)
        stop = threading.Event()
        errors = []

        def ingest():
            try:
                for i in range(40):
                    seg.index_batch(make_rows(256, seed=i,
                                              with_nulls=False))
                    seg.chunklet_index.promote()
                    time.sleep(0.001)
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))
            finally:
                stop.set()

        t = threading.Thread(target=ingest)
        t.start()
        last = 0
        while not stop.is_set():
            r = eng.execute("SELECT COUNT(*) FROM rt")
            assert not r.get("exceptions"), r
            c = r["resultTable"]["rows"][0][0]
            assert c >= last
            last = c
        t.join()
        assert not errors, errors
        assert rows_of(eng, "SELECT COUNT(*) FROM rt") == [[40 * 256]]


class TestProcessHarness:
    def test_ingest_worker_subprocess(self):
        """The per-partition OS-process consume loop (the multi-partition
        bench harness) runs standalone and reports its rows/s."""
        import json
        import os
        import subprocess
        import sys

        spec = json.dumps({"rows": 30_000, "partition": 3,
                           "rows_per_chunklet": 8192, "payload": "json"})
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable, "-m", "pinot_tpu.realtime.chunklet", spec],
            capture_output=True, timeout=120, env=env)
        assert out.returncode == 0, out.stderr.decode()[-2000:]
        rep = json.loads(out.stdout)
        assert rep["rows"] == 30_000 and rep["errors"] == 0
        assert rep["chunklets"] == 30_000 // 8192
        assert rep["rows_per_s"] > 0


class TestConfig:
    def test_chunklet_config_json_roundtrip(self):
        cfg = TableConfig(
            table_name="t",
            chunklets=ChunkletConfig(enabled=False, rows_per_chunklet=2048,
                                     device_min_rows=123))
        cfg2 = TableConfig.from_json(cfg.to_json())
        assert cfg2.chunklets == cfg.chunklets
        seg = MutableSegment(
            Schema.build(name="t", dimensions=[("d", DataType.STRING)],
                         metrics=[("m", DataType.INT)]),
            "s", cfg2)
        assert seg.chunklet_index is None  # disabled honors the knob
