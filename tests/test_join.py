"""Multi-stage engine v2: device hash joins — differential suite.

Inner/left equi-joins agree across the device kernels (ops/join.py), the
host (numpy) mirror, and a sqlite3 oracle, on sealed + consuming segments,
solo + 8-virtual-device mesh, with both BROADCAST and SHUFFLE strategies
forced via SET joinStrategy. Also pins:

- LOOKUP(...) transform results bit-identical to the equivalent LEFT JOIN
  (the broadcast-join path is a strict superset of the dim-table lookup),
- typed parser/analysis diagnostics (unknown/ambiguous columns name the
  alias and candidates),
- EXPLAIN rendering of the two-stage plan,
- literal-free query-log template keys for join shapes,
- broker-side two-stage execution over a 2-server cluster.
"""

import math
import sqlite3
import time

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.engine.device import DeviceExecutor
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.parallel.mesh import make_mesh
from pinot_tpu.sql.parser import SqlAnalysisError, parse_sql
from pinot_tpu.storage.creator import build_segment

N_FACT = 4000
N_PARTS = 60
N_CUSTS = 25


def _schemas():
    fact = Schema.build(
        name="orders",
        dimensions=[("partkey", DataType.INT), ("custkey", DataType.INT),
                    ("status", DataType.STRING)],
        metrics=[("qty", DataType.INT), ("price", DataType.DOUBLE)],
    )
    parts = Schema.build(
        name="parts",
        dimensions=[("pkey", DataType.INT), ("category", DataType.STRING),
                    ("brand", DataType.STRING)],
        primary_key_columns=["pkey"],
    )
    custs = Schema.build(
        name="custs",
        dimensions=[("ckey", DataType.INT), ("region", DataType.STRING)],
        primary_key_columns=["ckey"],
    )
    return fact, parts, custs


def _data(rng):
    # partkey range deliberately exceeds the dim table (misses for LEFT);
    # every key appears on many fact rows (duplicate probe keys)
    fact = {
        "partkey": rng.integers(0, N_PARTS + 8, N_FACT).astype(np.int32),
        "custkey": rng.integers(0, N_CUSTS, N_FACT).astype(np.int32),
        "status": np.array(["open", "paid", "void"])[
            rng.integers(0, 3, N_FACT)],
        "qty": rng.integers(1, 50, N_FACT).astype(np.int32),
        "price": np.round(rng.uniform(1.0, 500.0, N_FACT), 2),
    }
    parts = {
        "pkey": np.arange(N_PARTS, dtype=np.int32),
        "category": np.array([f"cat_{i % 7}" for i in range(N_PARTS)]),
        "brand": np.array([f"brand_{i % 11}" for i in range(N_PARTS)]),
    }
    custs = {
        "ckey": np.arange(N_CUSTS, dtype=np.int32),
        "region": np.array([f"region_{i % 5}" for i in range(N_CUSTS)]),
    }
    return fact, parts, custs


def _load_engine(engine, base, fact, parts, custs, tag):
    fact_schema, parts_schema, custs_schema = _schemas()
    half = N_FACT // 2
    for i, sl in enumerate([slice(0, half), slice(half, N_FACT)]):
        seg = build_segment(
            fact_schema, {k: v[sl] for k, v in fact.items()},
            str(base / f"f{tag}{i}"), TableConfig(table_name="orders"),
            f"f{i}")
        engine.add_segment("orders", seg)
    engine.add_segment("parts", build_segment(
        parts_schema, parts, str(base / f"p{tag}"),
        TableConfig(table_name="parts", is_dim_table=True), "p0"))
    engine.add_segment("custs", build_segment(
        custs_schema, custs, str(base / f"c{tag}"),
        TableConfig(table_name="custs", is_dim_table=True), "c0"))
    engine.table("parts").is_dim_table = True
    engine.table("custs").is_dim_table = True
    return engine


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    rng = np.random.default_rng(11)
    fact, parts, custs = _data(rng)
    base = tmp_path_factory.mktemp("joinseg")
    engines = {
        "host": _load_engine(QueryEngine(device_executor=None), base,
                             fact, parts, custs, "h"),
        "device": _load_engine(QueryEngine(), base, fact, parts, custs,
                               "d"),
        "mesh": _load_engine(
            QueryEngine(device_executor=DeviceExecutor(mesh=make_mesh(8))),
            base, fact, parts, custs, "m"),
    }
    con = sqlite3.connect(":memory:")
    con.execute("CREATE TABLE orders (partkey INT, custkey INT, "
                "status TEXT, qty INT, price REAL)")
    con.executemany(
        "INSERT INTO orders VALUES (?,?,?,?,?)",
        list(zip(*(fact[c].tolist() for c in
                   ("partkey", "custkey", "status", "qty", "price")))))
    con.execute("CREATE TABLE parts (pkey INT, category TEXT, brand TEXT)")
    con.executemany("INSERT INTO parts VALUES (?,?,?)",
                    list(zip(*(parts[c].tolist() for c in
                               ("pkey", "category", "brand")))))
    con.execute("CREATE TABLE custs (ckey INT, region TEXT)")
    con.executemany("INSERT INTO custs VALUES (?,?)",
                    list(zip(*(custs[c].tolist() for c in
                               ("ckey", "region")))))
    return engines, con


def _norm(v):
    if v is None:
        return None
    if isinstance(v, bool):
        return float(v)
    if isinstance(v, (int, float)):
        f = float(v)
        return None if math.isnan(f) else round(f, 6)
    return v


def _rows(resp):
    assert not resp.get("exceptions"), resp.get("exceptions")
    return [[_norm(v) for v in r] for r in resp["resultTable"]["rows"]]


def check(setup, sql, oracle_sql, engines=("host", "device", "mesh"),
          strategies=("broadcast", "shuffle")):
    eng_map, con = setup
    expected = [[_norm(v) for v in r]
                for r in con.execute(oracle_sql).fetchall()]
    for name in engines:
        for strat in strategies:
            full = f"SET joinStrategy='{strat}'; {sql}"
            got = _rows(eng_map[name].execute(full))
            assert got == expected, (
                f"{name}/{strat} mismatch for {sql!r}:\n"
                f"got      {got[:5]}\nexpected {expected[:5]}")


class TestJoinParity:
    def test_inner_group_by(self, setup):
        check(
            setup,
            "SELECT p.category, SUM(o.qty) FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey "
            "GROUP BY p.category ORDER BY p.category LIMIT 20",
            "SELECT p.category, SUM(o.qty) FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey "
            "GROUP BY p.category ORDER BY p.category LIMIT 20")

    def test_left_join_group_by(self, setup):
        # LEFT misses fill with the column TYPE default ('' for strings) —
        # the LOOKUP convention; COALESCE makes the oracle agree
        check(
            setup,
            "SELECT p.category, COUNT(*) FROM orders o "
            "LEFT JOIN parts p ON o.partkey = p.pkey "
            "GROUP BY p.category ORDER BY p.category LIMIT 20",
            "SELECT COALESCE(p.category, ''), COUNT(*) FROM orders o "
            "LEFT JOIN parts p ON o.partkey = p.pkey "
            "GROUP BY COALESCE(p.category, '') "
            "ORDER BY COALESCE(p.category, '') LIMIT 20")

    def test_inner_selection_order_by(self, setup):
        check(
            setup,
            "SELECT o.partkey, p.brand, o.qty FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey "
            "WHERE o.qty > 47 AND p.category = 'cat_3' "
            "ORDER BY o.partkey, o.qty LIMIT 15",
            "SELECT o.partkey, p.brand, o.qty FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey "
            "WHERE o.qty > 47 AND p.category = 'cat_3' "
            "ORDER BY o.partkey, o.qty LIMIT 15")

    def test_where_pushdown_both_sides(self, setup):
        check(
            setup,
            "SELECT p.category, COUNT(*), AVG(o.price) FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey "
            "WHERE o.status = 'paid' AND p.brand = 'brand_2' "
            "GROUP BY p.category ORDER BY p.category",
            "SELECT p.category, COUNT(*), AVG(o.price) FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey "
            "WHERE o.status = 'paid' AND p.brand = 'brand_2' "
            "GROUP BY p.category ORDER BY p.category")

    def test_residual_on_conjunct(self, setup):
        # non-equi ON conjunct evaluated on matched pairs (LEFT keeps
        # disqualified probe rows with default fill)
        check(
            setup,
            "SELECT p.category, COUNT(*) FROM orders o "
            "LEFT JOIN parts p ON o.partkey = p.pkey AND o.qty < 10 "
            "GROUP BY p.category ORDER BY p.category",
            "SELECT COALESCE(p.category, ''), COUNT(*) FROM orders o "
            "LEFT JOIN parts p ON o.partkey = p.pkey AND o.qty < 10 "
            "GROUP BY COALESCE(p.category, '') "
            "ORDER BY COALESCE(p.category, '')")

    def test_star_two_dim_chain(self, setup):
        check(
            setup,
            "SELECT p.category, c.region, SUM(o.price) FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey "
            "JOIN custs c ON o.custkey = c.ckey "
            "WHERE o.status <> 'void' "
            "GROUP BY p.category, c.region "
            "ORDER BY p.category, c.region LIMIT 50",
            "SELECT p.category, c.region, SUM(o.price) FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey "
            "JOIN custs c ON o.custkey = c.ckey "
            "WHERE o.status <> 'void' "
            "GROUP BY p.category, c.region "
            "ORDER BY p.category, c.region LIMIT 50")

    def test_multi_column_key(self, setup):
        # two-column equi-key (category+brand joined back on itself via a
        # derived fact column pair is overkill; use pkey twice to prove
        # multi-key packing)
        check(
            setup,
            "SELECT COUNT(*) FROM orders o JOIN parts p "
            "ON o.partkey = p.pkey AND o.partkey = p.pkey",
            "SELECT COUNT(*) FROM orders o JOIN parts p "
            "ON o.partkey = p.pkey")

    def test_having_on_join(self, setup):
        check(
            setup,
            "SELECT p.category, SUM(o.qty) FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey "
            "GROUP BY p.category HAVING SUM(o.qty) > 6000 "
            "ORDER BY p.category",
            "SELECT p.category, SUM(o.qty) FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey "
            "GROUP BY p.category HAVING SUM(o.qty) > 6000 "
            "ORDER BY p.category")

    def test_inner_join_no_matches(self, setup):
        check(
            setup,
            "SELECT COUNT(*), SUM(o.qty) FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey WHERE p.category = 'nope'",
            "SELECT COUNT(*), SUM(o.qty) FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey WHERE p.category = 'nope'")

    def test_join_strategy_reported(self, setup):
        eng_map, _ = setup
        r = eng_map["device"].execute(
            "SET joinStrategy='shuffle'; SELECT COUNT(*) FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey")
        assert r["joinStrategy"] == "SHUFFLE"
        assert r["numStages"] == 2
        r = eng_map["device"].execute(
            "SELECT COUNT(*) FROM orders o JOIN parts p "
            "ON o.partkey = p.pkey")
        # both dims are flagged is_dim_table: default strategy = BROADCAST
        assert r["joinStrategy"] == "BROADCAST"


class TestConsumingJoin:
    @pytest.fixture(scope="class")
    def consuming(self, tmp_path_factory):
        from pinot_tpu.storage.mutable import MutableSegment

        rng = np.random.default_rng(13)
        fact, parts, custs = _data(rng)
        base = tmp_path_factory.mktemp("joinrt")
        engines = {}
        for name, dev in (("host", None), ("device", "auto")):
            eng = QueryEngine() if dev else QueryEngine(device_executor=None)
            fact_schema, parts_schema, _ = _schemas()
            half = N_FACT // 2
            seg = build_segment(
                fact_schema, {k: v[:half] for k, v in fact.items()},
                str(base / f"f{name}"), TableConfig(table_name="orders"),
                "f0")
            eng.add_segment("orders", seg)
            ms = MutableSegment(fact_schema, "orders__0__0__rt")
            rows = [{k: fact[k][i].item() for k in fact}
                    for i in range(half, N_FACT)]
            ms.index_batch(rows)
            eng.add_segment("orders", ms)
            eng.add_segment("parts", build_segment(
                parts_schema, parts, str(base / f"p{name}"),
                TableConfig(table_name="parts", is_dim_table=True), "p0"))
            engines[name] = eng
        con = sqlite3.connect(":memory:")
        con.execute("CREATE TABLE orders (partkey INT, custkey INT, "
                    "status TEXT, qty INT, price REAL)")
        con.executemany(
            "INSERT INTO orders VALUES (?,?,?,?,?)",
            list(zip(*(fact[c].tolist() for c in
                       ("partkey", "custkey", "status", "qty", "price")))))
        con.execute("CREATE TABLE parts (pkey INT, category TEXT, "
                    "brand TEXT)")
        con.executemany(
            "INSERT INTO parts VALUES (?,?,?)",
            list(zip(*(parts[c].tolist() for c in
                       ("pkey", "category", "brand")))))
        return engines, con

    @pytest.mark.parametrize("strategy", ["broadcast", "shuffle"])
    def test_sealed_plus_consuming_parity(self, consuming, strategy):
        engines, con = consuming
        sql = ("SELECT p.category, COUNT(*), SUM(o.qty) FROM orders o "
               "JOIN parts p ON o.partkey = p.pkey "
               "GROUP BY p.category ORDER BY p.category")
        expected = [[_norm(v) for v in r]
                    for r in con.execute(sql).fetchall()]
        for name, eng in engines.items():
            got = _rows(eng.execute(f"SET joinStrategy='{strategy}'; {sql}"))
            assert got == expected, f"{name}/{strategy}"

    def test_left_join_on_consuming(self, consuming):
        engines, con = consuming
        sql = ("SELECT o.partkey, p.category FROM orders o "
               "LEFT JOIN parts p ON o.partkey = p.pkey "
               "WHERE o.qty = 7 ORDER BY o.partkey, p.category LIMIT 25")
        expected = [[_norm(v) for v in r] for r in con.execute(
            "SELECT o.partkey, COALESCE(p.category,'') FROM orders o "
            "LEFT JOIN parts p ON o.partkey = p.pkey "
            "WHERE o.qty = 7 ORDER BY o.partkey, COALESCE(p.category,'') "
            "LIMIT 25").fetchall()]
        for name, eng in engines.items():
            assert _rows(eng.execute(sql)) == expected, name


class TestLookupSuperset:
    """The broadcast join subsumes the LOOKUP transform: pin the LEFT JOIN
    bit-identical to LOOKUP against the same dim table."""

    def test_left_join_matches_lookup_bit_identical(self, setup):
        eng_map, _ = setup
        for name in ("host", "device", "mesh"):
            eng = eng_map[name]
            via_lookup = eng.execute(
                "SELECT partkey, LOOKUP('parts', 'category', 'pkey', "
                "partkey), qty FROM orders ORDER BY partkey, qty, "
                "LOOKUP('parts', 'category', 'pkey', partkey) LIMIT 200")
            via_join = eng.execute(
                "SELECT o.partkey, p.category, o.qty FROM orders o "
                "LEFT JOIN parts p ON o.partkey = p.pkey "
                "ORDER BY o.partkey, o.qty, p.category LIMIT 200")
            assert not via_lookup.get("exceptions")
            assert not via_join.get("exceptions")
            # bit-identical: same values, same types, incl. '' miss fills
            assert via_join["resultTable"]["rows"] == \
                via_lookup["resultTable"]["rows"], name

    def test_lookup_numeric_default_matches_left_join(self, setup):
        eng_map, _ = setup
        eng = eng_map["device"]
        via_lookup = eng.execute(
            "SELECT SUM(LOOKUP('parts', 'pkey', 'pkey', partkey)) "
            "FROM orders")
        via_join = eng.execute(
            "SELECT SUM(p.pkey) FROM orders o LEFT JOIN parts p "
            "ON o.partkey = p.pkey")
        assert via_join["resultTable"]["rows"] == \
            via_lookup["resultTable"]["rows"]


class TestDiagnostics:
    def test_unknown_column_names_alias_and_candidates(self, setup):
        eng_map, _ = setup
        r = eng_map["host"].execute(
            "SELECT p.nosuch FROM orders o JOIN parts p "
            "ON o.partkey = p.pkey")
        msg = r["exceptions"][0]["message"]
        assert "nosuch" in msg and "'p'" in msg and "category" in msg

    def test_unknown_bare_column_lists_tables(self, setup):
        eng_map, _ = setup
        r = eng_map["host"].execute(
            "SELECT nosuch FROM orders o JOIN parts p "
            "ON o.partkey = p.pkey")
        msg = r["exceptions"][0]["message"]
        assert "nosuch" in msg and "o(" in msg and "p(" in msg

    def test_ambiguous_column_names_candidate_aliases(self, tmp_path):
        # two tables sharing a column name: the bare reference must error
        # with both qualification options
        schema = Schema.build(
            name="t1", dimensions=[("k", DataType.INT)],
            metrics=[("v", DataType.INT)])
        eng = QueryEngine(device_executor=None)
        data = {"k": np.arange(4, dtype=np.int32),
                "v": np.arange(4, dtype=np.int32)}
        eng.add_segment("t1", build_segment(
            schema, data, str(tmp_path / "a"),
            TableConfig(table_name="t1"), "a0"))
        eng.add_segment("t2", build_segment(
            Schema.build(name="t2", dimensions=[("k", DataType.INT)],
                         metrics=[("v", DataType.INT)]),
            data, str(tmp_path / "b"), TableConfig(table_name="t2"), "b0"))
        r = eng.execute(
            "SELECT v FROM t1 a JOIN t2 b ON a.k = b.k")
        msg = r["exceptions"][0]["message"]
        assert "ambiguous" in msg and "a.v" in msg and "b.v" in msg

    def test_analysis_error_is_typed(self):
        from pinot_tpu.query2.logical import compile_plan

        stmt = parse_sql("SELECT x.nope FROM f x JOIN d y ON x.a = y.b")

        def catalog(table):
            return ("a", "b"), False

        with pytest.raises(SqlAnalysisError) as ei:
            compile_plan(stmt, catalog)
        assert ei.value.column == "x.nope"
        assert "a" in ei.value.candidates

    def test_non_equi_join_rejected(self, setup):
        eng_map, _ = setup
        r = eng_map["host"].execute(
            "SELECT COUNT(*) FROM orders o JOIN parts p "
            "ON o.partkey > p.pkey")
        assert "equality" in r["exceptions"][0]["message"]

    def test_right_join_rejected(self):
        with pytest.raises(Exception) as ei:
            parse_sql("SELECT 1 FROM a RIGHT JOIN b ON a.x = b.y")
        assert "RIGHT" in str(ei.value)

    def test_acl_checks_every_joined_table(self):
        # a restricted principal must not read a denied table THROUGH a
        # join: the broker HTTP ACL walks every referenced table
        from pinot_tpu.broker.http_api import BrokerHttpServer
        from pinot_tpu.common.auth import BasicAuthAccessControl

        srv = BrokerHttpServer.__new__(BrokerHttpServer)
        srv._access = BasicAuthAccessControl(
            {"bob": "pw"}, {"bob": ["orders"]})
        assert srv._denied_table(
            "bob", "SELECT COUNT(*) FROM orders") is None
        assert srv._denied_table(
            "bob", "SELECT COUNT(*) FROM orders o JOIN secrets s "
                   "ON o.k = s.k") == "secrets"

    def test_single_table_alias_still_single_stage(self, setup):
        # plain aliased single-table SQL stays on the v1 path (numStages
        # absent) and qualified refs resolve
        eng_map, _ = setup
        r = eng_map["host"].execute(
            "SELECT o.status, COUNT(*) FROM orders o "
            "WHERE o.qty > 10 GROUP BY o.status ORDER BY o.status")
        assert not r.get("exceptions")
        assert "numStages" not in r
        assert len(r["resultTable"]["rows"]) == 3

    def test_table_name_qualified_single_table(self, setup):
        # SELECT t.c FROM t (no alias): the table name itself qualifies
        eng_map, _ = setup
        r = eng_map["host"].execute(
            "SELECT orders.status, COUNT(*) FROM orders "
            "WHERE orders.qty > 10 GROUP BY orders.status "
            "ORDER BY orders.status")
        assert not r.get("exceptions"), r.get("exceptions")
        assert len(r["resultTable"]["rows"]) == 3

    def test_mixed_type_join_keys_never_match(self, setup):
        # strict typing: int = string equi-keys match nothing (sqlite's
        # int = text is false), instead of str-casting both sides
        eng_map, _ = setup
        for name in ("host", "device"):
            r = eng_map[name].execute(
                "SELECT COUNT(*) FROM orders o JOIN parts p "
                "ON o.partkey = p.category")
            assert not r.get("exceptions"), r.get("exceptions")
            assert r["resultTable"]["rows"][0][0] == 0, name
            # LEFT keeps every probe row, all misses
            r = eng_map[name].execute(
                "SELECT COUNT(*) FROM orders o LEFT JOIN parts p "
                "ON o.partkey = p.category")
            assert r["resultTable"]["rows"][0][0] == N_FACT, name

    def test_heuristic_broadcast_demotes_on_huge_build(self, setup,
                                                       monkeypatch):
        # an unforced BROADCAST must not replicate a build table past the
        # cap; SET joinStrategy='broadcast' still overrides
        from pinot_tpu.query2 import runner as runner_mod

        eng_map, _ = setup
        monkeypatch.setattr(runner_mod, "BROADCAST_MAX_BUILD_ROWS", 10)
        r = eng_map["host"].execute(
            "SELECT COUNT(*) FROM orders o JOIN parts p "
            "ON o.partkey = p.pkey")
        assert r["joinStrategy"] == "SHUFFLE"
        r = eng_map["host"].execute(
            "SET joinStrategy='broadcast'; SELECT COUNT(*) FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey")
        assert r["joinStrategy"] == "BROADCAST"


class TestExplainJoin:
    def test_explain_broadcast_inner(self, setup):
        eng_map, _ = setup
        r = eng_map["device"].execute(
            "SET joinStrategy='broadcast'; EXPLAIN PLAN FOR "
            "SELECT p.category, SUM(o.qty) FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey GROUP BY p.category")
        lines = [row[0] for row in r["resultTable"]["rows"]]
        text = "\n".join(lines)
        assert any("JOIN_INNER(strategy=BROADCAST" in ln for ln in lines)
        assert any("STAGE_BOUNDARY" in ln for ln in lines)
        assert "build=p=parts dim" in text and "probe=o=orders" in text
        assert any("KEYS(o.partkey = p.pkey)" in ln for ln in lines)
        assert any(ln.strip().startswith("SCAN(o=orders") for ln in lines)

    def test_explain_shuffle_left_with_pushdown(self, setup):
        eng_map, _ = setup
        r = eng_map["host"].execute(
            "SET joinStrategy='shuffle'; EXPLAIN PLAN FOR "
            "SELECT o.partkey FROM orders o LEFT JOIN parts p "
            "ON o.partkey = p.pkey WHERE o.qty > 5")
        lines = [row[0] for row in r["resultTable"]["rows"]]
        assert any("JOIN_LEFT(strategy=SHUFFLE" in ln for ln in lines)
        # probe-side WHERE pushes into the scan
        assert any("FILTER" in ln and "qty" in ln for ln in lines)

    def test_explain_mesh_exchange(self, setup):
        eng_map, _ = setup
        r = eng_map["mesh"].execute(
            "EXPLAIN PLAN FOR SELECT COUNT(*) FROM orders o "
            "JOIN parts p ON o.partkey = p.pkey")
        lines = [row[0] for row in r["resultTable"]["rows"]]
        assert any("mesh-collective" in ln for ln in lines)


class TestQuerylogTemplates:
    def test_join_template_literal_free(self, setup):
        from pinot_tpu.broker.querylog import template_key
        from pinot_tpu.query2.logical import compile_plan

        eng_map, _ = setup

        def catalog(table):
            cols = {"orders": ("partkey", "custkey", "status", "qty",
                               "price"),
                    "parts": ("pkey", "category", "brand")}[table]
            return cols, table == "parts"

        def key_for(sql):
            return template_key(compile_plan(parse_sql(sql), catalog))

        a = key_for("SELECT p.category, SUM(o.qty) FROM orders o "
                    "JOIN parts p ON o.partkey = p.pkey "
                    "WHERE o.qty > 5 GROUP BY p.category")
        b = key_for("SELECT p.category, SUM(o.qty) FROM orders o "
                    "JOIN parts p ON o.partkey = p.pkey "
                    "WHERE o.qty > 99 GROUP BY p.category")
        c = key_for("SELECT p.category, SUM(o.qty) FROM orders o "
                    "LEFT JOIN parts p ON o.partkey = p.pkey "
                    "WHERE o.qty > 5 GROUP BY p.category")
        assert a == b          # literals don't change the template
        assert a != c          # join kind does
        assert "joins[" in a and "INNER" in a
        assert "5" not in a and "99" not in b

    def test_window_template_covers_shape(self):
        from pinot_tpu.broker.querylog import template_key
        from pinot_tpu.query2.logical import compile_plan

        def catalog(table):
            return ("team", "score"), False

        k1 = template_key(compile_plan(parse_sql(
            "SELECT team, ROW_NUMBER() OVER (PARTITION BY team "
            "ORDER BY score) FROM games WHERE score > 3"), catalog))
        k2 = template_key(compile_plan(parse_sql(
            "SELECT team, ROW_NUMBER() OVER (PARTITION BY team "
            "ORDER BY score) FROM games WHERE score > 888"), catalog))
        assert k1 == k2
        assert "windows[row_number" in k1
        assert "888" not in k2


def _wait_until(cond, timeout=20.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


class TestBrokerMultistage:
    def test_join_via_broker_cluster(self, tmp_path):
        from pinot_tpu.broker.broker import Broker
        from pinot_tpu.cluster.registry import ClusterRegistry
        from pinot_tpu.controller.controller import Controller
        from pinot_tpu.server.server import ServerInstance

        rng = np.random.default_rng(17)
        fact, parts, _ = _data(rng)
        fact_schema, parts_schema, _ = _schemas()
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        servers = [
            ServerInstance(f"server_{i}", registry, str(tmp_path / f"s{i}"),
                           device_executor=None)
            for i in range(2)
        ]
        for s in servers:
            s.start()
        broker = Broker(registry, timeout_s=15.0)
        try:
            dim_cfg = TableConfig(table_name="parts", is_dim_table=True)
            controller.add_table(dim_cfg, parts_schema)
            build_segment(parts_schema, parts, str(tmp_path / "pup"),
                          dim_cfg, "p0")
            controller.upload_segment("parts", str(tmp_path / "pup"))
            fact_cfg = TableConfig(table_name="orders")
            controller.add_table(fact_cfg, fact_schema)
            half = N_FACT // 2
            for i, sl in enumerate([slice(0, half), slice(half, N_FACT)]):
                build_segment(fact_schema,
                              {k: v[sl] for k, v in fact.items()},
                              str(tmp_path / f"fup{i}"), fact_cfg, f"f{i}")
                controller.upload_segment("orders",
                                          str(tmp_path / f"fup{i}"))
            assert _wait_until(lambda: all(
                "parts_OFFLINE" in s.engine.tables
                and s.engine.tables["parts_OFFLINE"].segments
                for s in servers))
            assert _wait_until(lambda: len(
                registry.external_view("orders_OFFLINE")) == 2)

            # oracle: embedded engine over the same data
            emb = QueryEngine(device_executor=None)
            emb.add_segment("orders", build_segment(
                fact_schema, fact, str(tmp_path / "femb"), fact_cfg, "fe"))
            emb.add_segment("parts", build_segment(
                parts_schema, parts, str(tmp_path / "pemb"), dim_cfg,
                "pe"))
            sql = ("SELECT p.category, COUNT(*), SUM(o.qty) FROM orders o "
                   "JOIN parts p ON o.partkey = p.pkey "
                   "WHERE o.status = 'paid' "
                   "GROUP BY p.category ORDER BY p.category")
            got = broker.execute(sql)
            assert not got.get("exceptions"), got
            assert got["joinStrategy"] == "BROADCAST"
            assert got["numStages"] == 2
            assert _rows(got) == _rows(emb.execute(sql))
        finally:
            broker.close()
            for s in servers:
                s.stop()
