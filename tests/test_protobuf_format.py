"""Protobuf input format (pinot-protobuf analog): descriptor-driven batch
reader + stream decoder, with a protoc-compiled descriptor set built at
test time (protoc ships in the build image)."""

import os
import shutil
import subprocess

import numpy as np
import pytest

PROTO = """
syntax = "proto3";
package bench;

message Click {
  string user = 1;
  int64 clicks = 2;
  double score = 3;
  repeated string tags = 4;
}
"""


@pytest.fixture(scope="module")
def descriptor(tmp_path_factory):
    if shutil.which("protoc") is None:
        pytest.skip("protoc not available")
    pytest.importorskip("google.protobuf")
    d = tmp_path_factory.mktemp("proto")
    src = d / "click.proto"
    src.write_text(PROTO)
    out = d / "click.desc"
    subprocess.run(
        ["protoc", f"--proto_path={d}", f"--descriptor_set_out={out}",
         str(src)], check=True, capture_output=True)
    return str(out)


def _messages(descriptor, rows):
    from pinot_tpu.ingestion.protobuf_io import load_message_class

    cls = load_message_class(descriptor, "bench.Click")
    out = []
    for r in rows:
        m = cls()
        m.user = r["user"]
        m.clicks = r["clicks"]
        m.score = r["score"]
        m.tags.extend(r["tags"])
        out.append(m)
    return out


ROWS = [
    {"user": "alice", "clicks": 2**40, "score": 1.25, "tags": ["a", "b"]},
    {"user": "bob", "clicks": 0, "score": -3.5, "tags": []},
    {"user": "碧", "clicks": 7, "score": 0.0, "tags": ["x"]},
]


class TestProtobufFormat:
    def test_delimited_roundtrip(self, descriptor, tmp_path):
        from pinot_tpu.ingestion import protobuf_io

        p = str(tmp_path / "data.pb")
        protobuf_io.write_delimited(p, _messages(descriptor, ROWS))
        rows = protobuf_io.read_delimited(p, descriptor, "bench.Click")
        assert [r["user"] for r in rows] == ["alice", "bob", "碧"]
        assert rows[0]["clicks"] == str(2**40) or rows[0]["clicks"] == 2**40
        assert rows[1]["tags"] == []

    def test_record_reader_to_segment(self, descriptor, tmp_path):
        from pinot_tpu.common.datatypes import DataType
        from pinot_tpu.common.schema import Schema
        from pinot_tpu.common.table_config import TableConfig
        from pinot_tpu.engine.engine import QueryEngine
        from pinot_tpu.ingestion import protobuf_io
        from pinot_tpu.ingestion.readers import (
            create_record_reader,
            rows_to_columns,
        )
        from pinot_tpu.storage.creator import build_segment

        rows = [{"user": f"u{i % 4}", "clicks": i, "score": 0.5 * i,
                 "tags": ["t"]} for i in range(400)]
        p = str(tmp_path / "data.pb")
        protobuf_io.write_delimited(p, _messages(descriptor, rows))
        reader = create_record_reader(
            "protobuf", descriptor_file=descriptor,
            message_name="bench.Click")
        schema = Schema.build(
            name="c", dimensions=[("user", DataType.STRING)],
            metrics=[("clicks", DataType.LONG)])
        cols = rows_to_columns(reader.read_rows(p), schema)
        seg = build_segment(schema, cols, str(tmp_path / "seg"),
                            TableConfig(table_name="c"), "s0")
        eng = QueryEngine(device_executor=None)
        eng.add_segment("c", seg)
        r = eng.execute("SELECT user, SUM(clicks) FROM c GROUP BY user "
                        "ORDER BY user")
        want = {f"u{j}": sum(i for i in range(400) if i % 4 == j)
                for j in range(4)}
        assert [(row[0], row[1]) for row in r["resultTable"]["rows"]] == \
            sorted((k, float(v)) for k, v in want.items())

    def test_stream_decoder(self, descriptor):
        from pinot_tpu.common.table_config import StreamConfig
        from pinot_tpu.stream.spi import get_decoder

        cfg = StreamConfig(
            stream_type="memory", topic="t", decoder="protobuf",
            properties={"protobuf.descriptor_file": descriptor,
                        "protobuf.message_name": "bench.Click"})
        dec = get_decoder("protobuf", cfg)
        msg = _messages(descriptor, ROWS[:1])[0]
        out = dec(msg.SerializeToString())
        assert out["user"] == "alice"

    def test_missing_props_raise(self, descriptor):
        from pinot_tpu.ingestion.readers import create_record_reader

        with pytest.raises(ValueError, match="descriptor_file"):
            create_record_reader("protobuf").read_rows("/tmp/x.pb")
