"""Extended aggregation functions: SUMPRECISION, IDSET, smart/raw HLL,
raw digests, ST_UNION, MV variants (AggregationFunctionType parity)."""

import base64
import gzip
import json

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("aggx")
    schema = Schema.build(
        name="t",
        dimensions=[("g", DataType.STRING)],
        metrics=[("v", DataType.LONG), ("lon", DataType.DOUBLE),
                 ("lat", DataType.DOUBLE)],
        multi_value_dimensions=[("tags", DataType.STRING),
                                ("scores", DataType.INT)],
    )
    rng = np.random.default_rng(6)
    n = 3000
    cols = {
        "g": np.array(["a", "b"])[rng.integers(0, 2, n)],
        "v": rng.integers(0, 1000, n).astype(np.int64),
        "lon": rng.uniform(-10, 10, n).round(3),
        "lat": rng.uniform(-10, 10, n).round(3),
        "tags": [list(np.array(["x", "y", "z"])[
            rng.integers(0, 3, rng.integers(0, 4))]) for _ in range(n)],
        "scores": [list(rng.integers(0, 50, rng.integers(1, 5)))
                   for _ in range(n)],
    }
    eng = QueryEngine(device_executor=None)
    # two segments: exercises the merge algebra of every new spec
    half = n // 2
    for i, sl in enumerate([slice(0, half), slice(half, n)]):
        part = {k: (v[sl] if isinstance(v, np.ndarray) else v[sl])
                for k, v in cols.items()}
        seg = build_segment(schema, part, str(tmp / f"s{i}"),
                            TableConfig(table_name="t"), f"s{i}")
        eng.add_segment("t", seg)
    return eng, cols


def rows(eng, sql):
    r = eng.execute(sql)
    assert not r.get("exceptions"), r
    return r["resultTable"]["rows"]


class TestExtendedAggs:
    def test_sumprecision_exact(self, engine):
        eng, cols = engine
        got = rows(eng, "SELECT g, SUMPRECISION(v) FROM t GROUP BY g ORDER BY g")
        for g, s in got:
            assert int(s) == int(cols["v"][cols["g"] == g].sum())

    def test_idset_roundtrip(self, engine):
        eng, cols = engine
        got = rows(eng, "SELECT IDSET(g) FROM t")
        decoded = json.loads(gzip.decompress(base64.b64decode(got[0][0])))
        assert decoded == ["a", "b"]

    def test_smart_hll_exact_below_threshold(self, engine):
        eng, cols = engine
        got = rows(eng, "SELECT DISTINCTCOUNTSMARTHLL(v) FROM t")
        assert got[0][0] == len(np.unique(cols["v"]))  # exact below 100k

    def test_smart_hll_switches_above_threshold(self, engine):
        eng, cols = engine
        got = rows(eng, "SELECT DISTINCTCOUNTSMARTHLL(v, 100) FROM t")
        true = len(np.unique(cols["v"]))
        assert abs(got[0][0] - true) / true < 0.1  # HLL estimate

    def test_raw_hll_blob(self, engine):
        eng, _ = engine
        got = rows(eng, "SELECT DISTINCTCOUNTRAWHLL(g) FROM t")
        regs = np.frombuffer(base64.b64decode(got[0][0]), dtype=np.int8)
        assert len(regs) == 1 << 10  # default log2m=10 registers

    def test_raw_tdigest_blob(self, engine):
        eng, _ = engine
        got = rows(eng, "SELECT PERCENTILERAWTDIGEST(v, 90) FROM t")
        d = json.loads(base64.b64decode(got[0][0]))
        assert d["means"] and d["weights"]

    def test_raw_tdigest_mv_blob(self, engine):
        """PERCENTILERAWEST_MV / PERCENTILERAWTDIGEST_MV — the last two
        reference AggregationFunctionType enum names: serialized digest
        over MV entry values."""
        eng, cols = engine
        for fn in ("PERCENTILERAWTDIGESTMV", "PERCENTILERAWESTMV"):
            got = rows(eng, f"SELECT {fn}(scores, 50) FROM t")
            d = json.loads(base64.b64decode(got[0][0]))
            assert d["means"] and d["weights"]
            # digest totals count every MV ENTRY, not every doc
            n_entries = sum(len(r) for r in cols["scores"])
            assert abs(sum(d["weights"]) - n_entries) < 1e-6

    def test_st_union_multipoint(self, engine):
        eng, _ = engine
        got = rows(eng, "SELECT STUNION(ST_POINT(lon, lat)) FROM t "
                        "WHERE lon < -9.9")
        assert got[0][0].startswith("MULTIPOINT (")

    def test_mv_variants(self, engine):
        eng, cols = engine
        got = rows(eng, "SELECT MINMAXRANGEMV(scores), "
                        "DISTINCTCOUNTHLLMV(tags) FROM t")
        flat = np.concatenate([np.asarray(r) for r in cols["scores"] if r])
        assert got[0][0] == float(flat.max() - flat.min())
        assert abs(got[0][1] - 3) <= 1  # 3 distinct tags, HLL estimate
        got = rows(eng, "SELECT g, PERCENTILEMV(scores, 50) FROM t "
                        "GROUP BY g ORDER BY g")
        for g, p in got:
            gf = np.concatenate([np.asarray(r) for r, gg in
                                 zip(cols["scores"], cols["g"])
                                 if gg == g and len(r)])
            assert abs(p - np.percentile(gf, 50)) <= 3

    def test_sumprecision_past_float53(self, tmp_path):
        """2^53+1 scale values must not round-trip through float (r3)."""
        schema = Schema.build(name="p", dimensions=[("k", DataType.STRING)],
                              metrics=[("v", DataType.LONG)])
        big = np.array([2**53 + 1, 2**53 + 1], dtype=np.int64)
        eng = QueryEngine(device_executor=None)
        eng.add_segment("p", build_segment(
            schema, {"k": np.array(["a", "a"]), "v": big},
            str(tmp_path / "s"), TableConfig(table_name="p"), "s0"))
        got = rows(eng, "SELECT SUMPRECISION(v) FROM p")
        assert int(got[0][0]) == 2 * (2**53 + 1)

    def test_raw_hll_mv_returns_blob(self, engine):
        eng, _ = engine
        got = rows(eng, "SELECT DISTINCTCOUNTRAWHLLMV(tags) FROM t")
        regs = np.frombuffer(base64.b64decode(got[0][0]), dtype=np.int8)
        assert len(regs) == 1 << 10

    def test_smart_tdigest_parameters_string(self, engine):
        eng, cols = engine
        got = rows(eng, "SELECT PERCENTILESMARTTDIGEST(v, 50, "
                        "'threshold=100') FROM t")
        assert abs(got[0][0] - np.percentile(cols["v"], 50)) < 30

    def test_fasthll_alias(self, engine):
        eng, cols = engine
        a = rows(eng, "SELECT FASTHLL(v) FROM t")[0][0]
        b = rows(eng, "SELECT DISTINCTCOUNTHLL(v) FROM t")[0][0]
        assert a == b
