"""Star-tree index tests: build, fit check, substitution correctness.

Reference analogs: StarTreeV2 builder tests + StarTreeClusterIntegrationTest
(star-tree answers must equal non-star-tree answers) + the metadata-only
NonScanBasedAggregationOperator path.
"""

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import IndexingConfig, StarTreeIndexConfig, TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment
from pinot_tpu.storage.startree import load_star_trees


@pytest.fixture(scope="module")
def engines(tmp_path_factory):
    rng = np.random.default_rng(31)
    n = 20_000
    cols = {
        "d_year": rng.integers(1992, 1999, n).astype(np.int32),
        "d_region": np.array(["AMERICA", "ASIA", "EUROPE", "AFRICA"])[rng.integers(0, 4, n)],
        "d_category": np.array([f"cat{i}" for i in range(12)])[rng.integers(0, 12, n)],
        "revenue": rng.integers(100, 100_000, n).astype(np.int64),
        "quantity": rng.integers(1, 50, n).astype(np.int32),
    }
    schema = Schema.build(
        name="ssb",
        dimensions=[
            ("d_year", DataType.INT),
            ("d_region", DataType.STRING),
            ("d_category", DataType.STRING),
        ],
        metrics=[("revenue", DataType.LONG), ("quantity", DataType.INT)],
    )
    st_cfg = StarTreeIndexConfig(
        dimensions_split_order=["d_year", "d_region", "d_category"],
        function_column_pairs=[
            "SUM__revenue", "COUNT__*", "MIN__revenue", "MAX__revenue",
            "SUM__quantity", "DISTINCTCOUNTHLL__quantity",
            "PERCENTILETDIGEST__revenue",
            "DISTINCTCOUNTBITMAP__quantity", "PERCENTILEEST__revenue",
            "SUMPRECISION__revenue",
        ],
    )
    cfg = TableConfig(
        table_name="ssb",
        indexing=IndexingConfig(star_tree_configs=[st_cfg]),
    )
    plain_cfg = TableConfig(table_name="ssb")
    base = tmp_path_factory.mktemp("stseg")
    st_engine = QueryEngine()
    plain_engine = QueryEngine()
    half = n // 2
    for i, sl in enumerate([slice(0, half), slice(half, n)]):
        part = {k: v[sl] for k, v in cols.items()}
        build_segment(schema, part, str(base / f"st{i}"), cfg, f"s{i}")
        build_segment(schema, part, str(base / f"plain{i}"), plain_cfg, f"s{i}")
        st_engine.add_segment("ssb", ImmutableSegment(str(base / f"st{i}")))
        plain_engine.add_segment("ssb", ImmutableSegment(str(base / f"plain{i}")))
    return st_engine, plain_engine, cols


def test_star_tree_built(engines, tmp_path_factory):
    st_engine, _, _ = engines
    seg = next(iter(st_engine.tables["ssb"].segments.values()))
    trees = load_star_trees(seg)
    assert len(trees) == 1
    meta, st_seg = trees[0]
    assert meta["dimensions_split_order"] == ["d_year", "d_region", "d_category"]
    assert st_seg.n_docs < seg.n_docs  # actually pre-aggregated
    assert "sum__revenue" in st_seg.column_names()


ST_QUERIES = [
    "SELECT SUM(revenue) FROM ssb",
    "SELECT SUM(revenue), COUNT(*) FROM ssb WHERE d_region = 'ASIA'",
    "SELECT d_year, SUM(revenue) FROM ssb GROUP BY d_year ORDER BY d_year",
    "SELECT d_region, d_year, SUM(revenue), COUNT(*) FROM ssb "
    "WHERE d_category IN ('cat1','cat5') GROUP BY d_region, d_year "
    "ORDER BY d_region, d_year LIMIT 50",
    "SELECT MIN(revenue), MAX(revenue) FROM ssb WHERE d_year BETWEEN 1994 AND 1996",
    "SELECT d_region, AVG(revenue) FROM ssb GROUP BY d_region ORDER BY d_region",
    "SELECT d_year, MINMAXRANGE(revenue) FROM ssb GROUP BY d_year ORDER BY d_year",
    "SELECT SUM(quantity) FROM ssb WHERE d_region != 'AFRICA'",
    # sketch pre-aggregation (DistinctCountHLLValueAggregator analog): the
    # cube's register planes must merge to BIT-IDENTICAL estimates vs the
    # scan path (same value hashing on both sides)
    "SELECT DISTINCTCOUNTHLL(quantity) FROM ssb",
    "SELECT DISTINCTCOUNTHLL(quantity) FROM ssb WHERE d_region = 'ASIA'",
    "SELECT d_year, COUNT(*), AVG(revenue), DISTINCTCOUNTHLL(quantity) "
    "FROM ssb GROUP BY d_year ORDER BY COUNT(*) DESC, d_year LIMIT 5",
]


@pytest.mark.parametrize("sql", ST_QUERIES)
def test_star_tree_matches_scan(engines, sql):
    """StarTreeClusterIntegrationTest semantics: identical answers with and
    without the index."""
    st_engine, plain_engine, _ = engines
    a = st_engine.execute(sql)
    b = plain_engine.execute(sql)
    assert not a.get("exceptions"), a
    assert a["resultTable"]["rows"] == b["resultTable"]["rows"], (
        a["resultTable"]["rows"][:4],
        b["resultTable"]["rows"][:4],
    )


def test_star_tree_actually_used(engines):
    st_engine, plain_engine, _ = engines
    a = st_engine.execute("SELECT d_year, SUM(revenue) FROM ssb GROUP BY d_year")
    b = plain_engine.execute("SELECT d_year, SUM(revenue) FROM ssb GROUP BY d_year")
    # pre-aggregated docs scanned << raw docs scanned
    assert a["numDocsScanned"] < b["numDocsScanned"] / 3, (
        a["numDocsScanned"], b["numDocsScanned"],
    )


def test_unfit_queries_fall_through(engines):
    st_engine, plain_engine, _ = engines
    # filter on a metric column: not covered by split dims → scan path
    sql = "SELECT SUM(revenue) FROM ssb WHERE quantity > 25"
    a = st_engine.execute(sql)
    b = plain_engine.execute(sql)
    assert a["resultTable"]["rows"] == b["resultTable"]["rows"]
    assert a["numDocsScanned"] == b["numDocsScanned"]  # full scan both

    # opt-out via query option (reference: useStarTree=false)
    opt = st_engine.execute(
        "SET useStarTree = false; SELECT SUM(revenue) FROM ssb WHERE d_region = 'ASIA'"
    )
    assert opt["resultTable"]["rows"] == plain_engine.execute(
        "SELECT SUM(revenue) FROM ssb WHERE d_region = 'ASIA'"
    )["resultTable"]["rows"]


def test_tdigest_pre_aggregation(engines):
    """Digest pair: cube answers within the documented rank-error bound of
    the scan path (pre-agg digests are approximate like the reference's —
    NOT bit-identical), and the cube is actually consulted."""
    st_engine, plain_engine, cols = engines
    sql = ("SELECT d_year, PERCENTILETDIGEST(revenue, 90) FROM ssb "
           "GROUP BY d_year ORDER BY d_year")
    a = st_engine.execute(sql)
    b = plain_engine.execute(sql)
    assert not a.get("exceptions"), a
    assert a["numDocsScanned"] < b["numDocsScanned"] / 3, (
        a["numDocsScanned"], b["numDocsScanned"])
    spread = float(cols["revenue"].max() - cols["revenue"].min())
    for ra, rb in zip(a["resultTable"]["rows"], b["resultTable"]["rows"]):
        assert ra[0] == rb[0]
        # both are digest approximations of the same data: within ~2% of
        # the value spread of each other (rank error ~1.5/delta each side)
        assert abs(ra[1] - rb[1]) < 0.02 * spread, (ra, rb)


def test_tdigest_compression_mismatch_falls_through(engines):
    st_engine, plain_engine, _ = engines
    sql = "SELECT PERCENTILETDIGEST(revenue, 50, 400) FROM ssb"
    a = st_engine.execute(sql)
    b = plain_engine.execute(sql)
    assert not a.get("exceptions"), a
    assert a["numDocsScanned"] == b["numDocsScanned"]  # scan on both
    assert a["resultTable"]["rows"] == b["resultTable"]["rows"]


def test_hll_pre_aggregation_used(engines):
    """The HLL query must run over cube rows, not raw docs."""
    st_engine, plain_engine, _ = engines
    sql = "SELECT d_year, DISTINCTCOUNTHLL(quantity) FROM ssb GROUP BY d_year"
    a = st_engine.execute(sql)
    b = plain_engine.execute(sql)
    assert a["resultTable"]["rows"] == b["resultTable"]["rows"]
    assert a["numDocsScanned"] < b["numDocsScanned"] / 3, (
        a["numDocsScanned"], b["numDocsScanned"])


def test_hll_log2m_mismatch_falls_through(engines):
    """A query at a different register resolution than the cube's must scan
    (merging planes of the wrong m would silently skew the estimate)."""
    st_engine, plain_engine, _ = engines
    sql = "SELECT DISTINCTCOUNTHLL(quantity, 8) FROM ssb"
    a = st_engine.execute(sql)
    b = plain_engine.execute(sql)
    assert not a.get("exceptions"), a
    assert a["resultTable"]["rows"] == b["resultTable"]["rows"]
    assert a["numDocsScanned"] == b["numDocsScanned"]  # scan on both


def test_metadata_only_path(engines):
    st_engine, _, cols = engines
    r = st_engine.execute("SELECT COUNT(*), MIN(revenue), MAX(revenue) FROM ssb")
    assert r["resultTable"]["rows"][0] == [
        len(cols["revenue"]),
        float(cols["revenue"].min()),
        float(cols["revenue"].max()),
    ]
    # zero entries scanned: straight off metadata
    assert r["numEntriesScannedPostFilter"] == 0


def test_bitmap_pair_exact(engines):
    """DISTINCTCOUNTBITMAP / DISTINCTCOUNT pair: EXACT cube==scan equality
    (DistinctCountBitmapValueAggregator analog), cube actually consulted."""
    st_engine, plain_engine, _ = engines
    for fn in ("DISTINCTCOUNTBITMAP", "DISTINCTCOUNT"):
        sql = (f"SELECT d_year, {fn}(quantity) FROM ssb "
               "WHERE d_region != 'AFRICA' GROUP BY d_year ORDER BY d_year")
        a = st_engine.execute(sql)
        b = plain_engine.execute(sql)
        assert not a.get("exceptions"), a
        assert a["resultTable"]["rows"] == b["resultTable"]["rows"]
        assert a["numDocsScanned"] < b["numDocsScanned"] / 3, (
            fn, a["numDocsScanned"], b["numDocsScanned"])


def test_sumprecision_pair_exact(engines):
    """SUMPRECISION pair: exact decimal re-sum equals the scan path."""
    st_engine, plain_engine, _ = engines
    sql = ("SELECT d_region, SUMPRECISION(revenue) FROM ssb "
           "GROUP BY d_region ORDER BY d_region")
    a = st_engine.execute(sql)
    b = plain_engine.execute(sql)
    assert not a.get("exceptions"), a
    assert a["resultTable"]["rows"] == b["resultTable"]["rows"]
    assert a["numDocsScanned"] < b["numDocsScanned"] / 3


def test_percentileest_pair(engines):
    """PERCENTILEEST / PERCENTILE route through the second digest pair
    (PercentileEstValueAggregator role) at the family default compression;
    answers agree with the scan path within the digest error bound."""
    st_engine, plain_engine, cols = engines
    spread = float(cols["revenue"].max() - cols["revenue"].min())
    for fn in ("PERCENTILEEST", "PERCENTILE"):
        sql = (f"SELECT d_year, {fn}(revenue, 75) FROM ssb "
               "GROUP BY d_year ORDER BY d_year")
        a = st_engine.execute(sql)
        b = plain_engine.execute(sql)
        assert not a.get("exceptions"), a
        assert a["numDocsScanned"] < b["numDocsScanned"] / 3, (
            fn, a["numDocsScanned"], b["numDocsScanned"])
        for ra, rb in zip(a["resultTable"]["rows"], b["resultTable"]["rows"]):
            assert ra[0] == rb[0]
            assert abs(ra[1] - rb[1]) < 0.02 * spread, (fn, ra, rb)
