"""Gapfill in broker reduce (GapfillProcessor analog, SET-option surface).

SET gapfillBucketMs = N enables filling of missing time buckets in a
single-bucket GROUP BY; gapfillStart/gapfillEnd bound the range and
gapfillFill picks zero | null | previous.
"""

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment

HOUR = 3_600_000


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("gap")
    schema = Schema.build(
        name="metrics",
        datetimes=[("ts", DataType.LONG)],
        metrics=[("v", DataType.LONG)],
    )
    # buckets 0,1,4,5 present; 2,3 missing
    ts = np.array([0, 0, HOUR, 4 * HOUR, 5 * HOUR, 5 * HOUR], dtype=np.int64)
    v = np.array([1, 2, 10, 40, 50, 5], dtype=np.int64)
    eng = QueryEngine(device_executor=None)
    seg = build_segment(schema, {"ts": ts, "v": v}, str(tmp / "s"),
                        TableConfig(table_name="metrics"), "s0")
    eng.add_segment("metrics", seg)
    return eng


def q(eng, sql):
    r = eng.execute(sql)
    assert not r.get("exceptions"), r
    return r["resultTable"]["rows"]


class TestGapfill:
    def test_zero_fill(self, engine):
        rows = q(engine,
                 f"SET gapfillBucketMs = {HOUR}; "
                 "SELECT ts - ts % 3600000, SUM(v) FROM metrics "
                 "GROUP BY ts - ts % 3600000 ORDER BY ts - ts % 3600000")
        assert rows == [[0, 3], [HOUR, 10], [2 * HOUR, 0], [3 * HOUR, 0],
                        [4 * HOUR, 40], [5 * HOUR, 55]]

    def test_null_fill(self, engine):
        rows = q(engine,
                 f"SET gapfillBucketMs = {HOUR}; SET gapfillFill = 'null'; "
                 "SELECT ts - ts % 3600000, SUM(v) FROM metrics "
                 "GROUP BY ts - ts % 3600000 ORDER BY ts - ts % 3600000")
        assert rows[2] == [2 * HOUR, None]
        assert rows[4] == [4 * HOUR, 40]

    def test_previous_fill(self, engine):
        rows = q(engine,
                 f"SET gapfillBucketMs = {HOUR}; "
                 "SET gapfillFill = 'previous'; "
                 "SELECT ts - ts % 3600000, COUNT(*) FROM metrics "
                 "GROUP BY ts - ts % 3600000 ORDER BY ts - ts % 3600000")
        # buckets 2,3 carry bucket 1's count
        assert [r[1] for r in rows] == [2, 1, 1, 1, 1, 2]

    def test_explicit_range(self, engine):
        rows = q(engine,
                 f"SET gapfillBucketMs = {HOUR}; "
                 f"SET gapfillStart = 0; SET gapfillEnd = {8 * HOUR}; "
                 "SELECT ts - ts % 3600000, SUM(v) FROM metrics "
                 "GROUP BY ts - ts % 3600000 ORDER BY ts - ts % 3600000")
        assert len(rows) == 8
        assert rows[-1] == [7 * HOUR, 0]

    def test_requires_single_group_by(self, engine):
        r = engine.execute(
            f"SET gapfillBucketMs = {HOUR}; "
            "SELECT ts, v, COUNT(*) FROM metrics GROUP BY ts, v")
        assert r["exceptions"]

    def test_misaligned_keys_error_not_silent_zeroes(self, engine):
        # off-grid keys must raise, not replace real data with fill (r3)
        r = engine.execute(
            f"SET gapfillBucketMs = {HOUR}; SET gapfillStart = 1800000; "
            "SELECT ts - ts % 3600000, SUM(v) FROM metrics "
            "GROUP BY ts - ts % 3600000")
        assert r["exceptions"]
        assert "aligned" in r["exceptions"][0]["message"]

    def test_zero_fill_keeps_count_integer(self, engine):
        r = engine.execute(
            f"SET gapfillBucketMs = {HOUR}; "
            "SELECT ts - ts % 3600000, COUNT(*) FROM metrics "
            "GROUP BY ts - ts % 3600000 ORDER BY ts - ts % 3600000")
        assert r["resultTable"]["dataSchema"]["columnDataTypes"][1] == "LONG"
        assert all(isinstance(row[1], int) for row in r["resultTable"]["rows"])

    def test_off_without_option(self, engine):
        rows = q(engine,
                 "SELECT ts - ts % 3600000, SUM(v) FROM metrics "
                 "GROUP BY ts - ts % 3600000 ORDER BY ts - ts % 3600000")
        assert len(rows) == 4  # only present buckets
