"""Randomized query generation vs the sqlite oracle.

Equivalent of the reference's QueryGenerator.java + H2 cross-checking
(pinot-integration-tests/.../QueryGenerator.java, run by the cluster
integration tests): seeded random aggregation/group-by/selection queries
with random filter trees, executed through the full engine pipeline and
compared row-for-row against sqlite3.
"""

import math
import sqlite3

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment

DIMS = ["city", "tier", "year"]
METRICS = ["clicks", "cost"]
N_QUERIES = 120


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    rng = np.random.default_rng(13)
    n = 5000
    cols = {
        "city": np.array([f"city_{i:02d}" for i in range(30)])[
            rng.integers(0, 30, n)],
        "tier": np.array(["gold", "silver", "bronze"])[rng.integers(0, 3, n)],
        "year": rng.integers(2015, 2025, n).astype(np.int32),
        "clicks": rng.integers(0, 1000, n).astype(np.int64),
        "cost": np.round(rng.uniform(0, 500, n), 3),
    }
    schema = Schema.build(
        name="ads",
        dimensions=[("city", DataType.STRING), ("tier", DataType.STRING),
                    ("year", DataType.INT)],
        metrics=[("clicks", DataType.LONG), ("cost", DataType.DOUBLE)],
    )
    cfg = TableConfig(
        table_name="ads",
        indexing=IndexingConfig(inverted_index_columns=["tier"]),
    )
    base = tmp_path_factory.mktemp("qgen")
    engine = QueryEngine(device_executor=None)
    third = n // 3
    for i, sl in enumerate(
            (slice(0, third), slice(third, 2 * third), slice(2 * third, n))):
        part = {k: v[sl] for k, v in cols.items()}
        engine.add_segment(
            "ads", build_segment(schema, part, str(base / f"s{i}"), cfg, f"s{i}"))
    con = sqlite3.connect(":memory:")
    con.execute("CREATE TABLE ads (city TEXT, tier TEXT, year INT, "
                "clicks INT, cost REAL)")
    con.executemany(
        "INSERT INTO ads VALUES (?,?,?,?,?)",
        list(zip(cols["city"].tolist(), cols["tier"].tolist(),
                 cols["year"].tolist(), cols["clicks"].tolist(),
                 cols["cost"].tolist())),
    )
    return engine, con, cols


class QueryGenerator:
    """Seeded random query source (QueryGenerator.java analog)."""

    AGGS = ["COUNT(*)", "SUM(clicks)", "MIN(clicks)", "MAX(clicks)",
            "AVG(clicks)", "SUM(cost)", "MIN(cost)", "MAX(cost)"]

    def __init__(self, cols, seed: int):
        self.rng = np.random.default_rng(seed)
        self.cols = cols

    def _raw_value(self, col: str):
        v = self.cols[col][self.rng.integers(len(self.cols[col]))]
        return v.item() if isinstance(v, np.generic) else v

    def _fmt(self, v) -> str:
        if isinstance(v, str):
            return f"'{v}'"
        return repr(v)

    def _value(self, col: str) -> str:
        return self._fmt(self._raw_value(col))

    def _predicate(self) -> str:
        col = [*DIMS, *METRICS][self.rng.integers(len(DIMS) + len(METRICS))]
        kind = self.rng.integers(4)
        if kind == 0:
            return f"{col} = {self._value(col)}"
        if kind == 1:
            return f"{col} <> {self._value(col)}"
        if kind == 2:
            vals = ", ".join(self._value(col)
                             for _ in range(int(self.rng.integers(1, 4))))
            return f"{col} IN ({vals})"
        lo, hi = sorted((self._raw_value(col), self._raw_value(col)))
        return f"({col} >= {self._fmt(lo)} AND {col} < {self._fmt(hi)})"

    def _where(self) -> str:
        k = int(self.rng.integers(0, 4))
        if k == 0:
            return ""
        preds = [self._predicate() for _ in range(k)]
        joiner = " AND " if self.rng.random() < 0.7 else " OR "
        return " WHERE " + joiner.join(preds)

    # time-transform expressions: (pinot form, sqlite-oracle form) — sqlite
    # integer division matches Java TimeUnit truncation on non-negative ints
    TIME_EXPRS = [
        ("TIMECONVERT(clicks, 'MILLISECONDS', 'SECONDS')",
         "(clicks / 1000)"),
        ("TIMECONVERT(clicks, 'MILLISECONDS', 'MINUTES')",
         "(clicks / 60000)"),
        ("DATETIMECONVERT(clicks, '1:MILLISECONDS:EPOCH', "
         "'1:SECONDS:EPOCH', '1:MINUTES')",
         "(((clicks / 60000) * 60000) / 1000)"),
        ("DATETIMECONVERT(clicks, '1:MILLISECONDS:EPOCH', "
         "'5:SECONDS:EPOCH', '5:SECONDS')",
         "(((clicks / 5000) * 5000) / 5000)"),
    ]

    def next_query(self):
        roll = self.rng.random()
        if roll < 0.1:  # time-rollup group-by (DATETIMECONVERT/TIMECONVERT)
            p_expr, s_expr = self.TIME_EXPRS[
                self.rng.integers(len(self.TIME_EXPRS))]
            agg = self.AGGS[self.rng.integers(len(self.AGGS))]
            where = self._where()
            return (
                f"SELECT {p_expr}, {agg} FROM ads{where} "
                f"GROUP BY {p_expr} ORDER BY {p_expr} LIMIT 100000",
                f"SELECT {s_expr}, {agg} FROM ads{where} "
                f"GROUP BY {s_expr} ORDER BY {s_expr} LIMIT 100000",
            )
        if roll < 0.45:  # scalar aggregation
            aggs = list(self.rng.choice(self.AGGS, size=int(self.rng.integers(1, 4)),
                                        replace=False))
            return f"SELECT {', '.join(aggs)} FROM ads{self._where()}"
        if roll < 0.85:  # group by, deterministically ordered
            n_g = int(self.rng.integers(1, 3))
            groups = list(self.rng.choice(DIMS, size=n_g, replace=False))
            aggs = list(self.rng.choice(self.AGGS, size=int(self.rng.integers(1, 3)),
                                        replace=False))
            having = ""
            if self.rng.random() < 0.25 and "COUNT(*)" in aggs:
                having = f" HAVING COUNT(*) > {int(self.rng.integers(1, 10))}"
            g = ", ".join(groups)
            return (f"SELECT {g}, {', '.join(aggs)} FROM ads{self._where()} "
                    f"GROUP BY {g}{having} ORDER BY {g} LIMIT 100000")
        # selection with a full-row total order (ties are identical rows)
        sel = [*DIMS, *METRICS]
        order = ", ".join(sel)
        return (f"SELECT {', '.join(sel)} FROM ads{self._where()} "
                f"ORDER BY {order} LIMIT 500")


def _norm(v):
    if v is None:
        return None
    if isinstance(v, (int, float, np.integer, np.floating)):
        f = float(v)
        return None if math.isnan(f) else round(f, 6)
    return v


def _diff(got, want):
    if len(got) != len(want):
        return f"row count {len(got)} != {len(want)}"
    for i, (rg, rw) in enumerate(zip(got, want)):
        ng = [_norm(x) for x in rg]
        nw = [_norm(x) for x in rw]
        for a, b in zip(ng, nw):
            if isinstance(a, float) and isinstance(b, float):
                if not math.isclose(a, b, rel_tol=1e-6, abs_tol=1e-6):
                    return f"row {i}: {ng} != {nw}"
            elif a != b:
                return f"row {i}: {ng} != {nw}"
    return None


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_random_queries_match_oracle(setup, seed):
    engine, con, cols = setup
    gen = QueryGenerator(cols, seed)
    failures = []
    for i in range(N_QUERIES):
        q = gen.next_query()
        sql, oracle_sql = q if isinstance(q, tuple) else (q, q)
        resp = engine.execute(sql)
        if resp.get("exceptions"):
            failures.append((sql, resp["exceptions"]))
            continue
        got = [tuple(r) for r in resp["resultTable"]["rows"]]
        want = [tuple(r) for r in con.execute(oracle_sql).fetchall()]
        err = _diff(got, want)
        if err:
            failures.append((sql, err))
    assert not failures, f"{len(failures)} mismatches; first: {failures[0]}"