"""Controller long-tail: tier relocation, config recommender, table tuner.

Reference analogs: relocation/SegmentRelocator.java,
recommender/RecommenderDriver.java, tuner/TableConfigTuner.java.
"""

import time

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.cluster.registry import ClusterRegistry, Role
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment


def wait_until(cond, timeout=10.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.05)
    return False


class TestTierRelocation:
    def test_aged_segments_move_to_tagged_servers(self, tmp_path):
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        hot = ServerInstance("hot_0", registry, str(tmp_path / "hot"),
                             device_executor=None)
        cold = ServerInstance("cold_0", registry, str(tmp_path / "cold"),
                              device_executor=None, tags=["cold_tier"])
        hot.start()
        cold.start()
        broker = Broker(registry)
        try:
            schema = Schema.build(name="t",
                                  dimensions=[("k", DataType.STRING)],
                                  metrics=[("v", DataType.INT)])
            day_ms = 86_400_000
            cfg = TableConfig(table_name="t", tiers=[
                {"name": "cold", "segment_age_ms": 7 * day_ms,
                 "server_tag": "cold_tier"}])
            controller.add_table(cfg, schema)
            d = str(tmp_path / "seg")
            build_segment(schema, {"k": np.array(["a", "b"] * 100),
                                   "v": np.arange(200, dtype=np.int32)},
                          d, cfg, "t_old")
            controller.upload_segment("t", d)
            d2 = str(tmp_path / "seg2")
            build_segment(schema, {"k": np.array(["c"] * 100),
                                   "v": np.arange(100, dtype=np.int32)},
                          d2, cfg, "t_new")
            controller.upload_segment("t", d2)

            # nothing is old enough yet: no movement
            assert controller.run_segment_relocation() == {}

            # age t_old past the tier threshold
            def age(s):
                recs = registry.segments("t_OFFLINE")
                recs["t_old"].push_time_ms -= 8 * day_ms
                registry.add_segment(recs["t_old"],
                                     registry.assignment("t_OFFLINE")["t_old"])

            age(registry)
            moved = controller.run_segment_relocation()
            assert moved["t_OFFLINE"]["t_old"]["to"] == ["cold_0"]
            assert moved["t_OFFLINE"]["t_old"]["tier"] == "cold"
            # servers reconcile: cold serves t_old, hot unloads it
            assert wait_until(
                lambda: "t_old" in cold.engine.tables.get(
                    "t_OFFLINE", type("e", (), {"segments": {}})).segments)
            assert wait_until(
                lambda: "t_old" not in hot.engine.tables.get(
                    "t_OFFLINE", type("e", (), {"segments": {}})).segments)
            # queries still see every row across tiers
            deadline = time.time() + 10
            while time.time() < deadline:
                r = broker.execute("SELECT COUNT(*) FROM t")
                if not r.get("exceptions") and \
                        r["resultTable"]["rows"][0][0] == 300:
                    break
                time.sleep(0.1)
            assert r["resultTable"]["rows"][0][0] == 300, r
            # idempotent: second run moves nothing
            assert controller.run_segment_relocation() == {}
        finally:
            broker.close()
            hot.stop()
            cold.stop()


class TestRecommender:
    def test_workload_driven_recommendation(self):
        registry = ClusterRegistry()
        schema = Schema.build(
            name="ads",
            dimensions=[("city", DataType.STRING), ("tier", DataType.STRING),
                        ("url", DataType.STRING)],
            metrics=[("clicks", DataType.LONG), ("cost", DataType.DOUBLE)],
        )
        queries = [
            "SELECT SUM(clicks) FROM ads WHERE city = 'nyc'",
            "SELECT COUNT(*) FROM ads WHERE city IN ('sf', 'la')",
            "SELECT SUM(cost) FROM ads WHERE clicks BETWEEN 10 AND 90",
            "SELECT COUNT(*) FROM ads WHERE clicks > 5 AND city = 'mia'",
            "SELECT city, tier, SUM(clicks), COUNT(*) FROM ads "
            "GROUP BY city, tier",
            "SELECT tier, city, COUNT(*), SUM(clicks) FROM ads "
            "GROUP BY tier, city",
            "SELECT COUNT(*) FROM ads WHERE REGEXP_LIKE(url, 'checkout')",
        ]
        from pinot_tpu.controller.controller import Controller
        import tempfile

        controller = Controller(registry, tempfile.mkdtemp())
        rec = controller.recommend_config(schema, queries, qps=200)
        idx = rec["indexing"]
        assert "city" in idx.inverted_index_columns
        assert rec["sorted_column"] == "city"  # most-filtered dimension
        assert "clicks" in idx.range_index_columns
        assert "url" in idx.fst_index_columns
        assert len(idx.star_tree_configs) == 1
        st = idx.star_tree_configs[0]
        assert sorted(st.dimensions_split_order) == ["city", "tier"]
        assert "SUM__clicks" in st.function_column_pairs
        assert rec["rationale"]  # human-readable reasons present

    def test_unparsable_queries_skipped(self):
        import tempfile

        registry = ClusterRegistry()
        controller = Controller(registry, tempfile.mkdtemp())
        schema = Schema.build(name="t", dimensions=[("k", DataType.STRING)],
                              metrics=[("v", DataType.INT)])
        rec = controller.recommend_config(schema, ["NOT SQL AT ALL"], qps=10)
        assert rec["indexing"].inverted_index_columns == []


class TestTuner:
    def test_tuner_grows_config_from_segment_stats(self, tmp_path):
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        server = ServerInstance("s0", registry, str(tmp_path / "srv"),
                                device_executor=None)
        server.start()
        self._run(tmp_path, registry, controller, server)

    def _run(self, tmp_path, registry, controller, server):
        schema = Schema.build(
            name="t",
            dimensions=[("low", DataType.STRING), ("high", DataType.STRING)],
            metrics=[("v", DataType.INT)],
        )
        cfg = TableConfig(table_name="t")
        controller.add_table(cfg, schema)
        n = 5000
        rng = np.random.default_rng(3)
        d = str(tmp_path / "seg")
        build_segment(schema, {
            "low": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
            "high": np.asarray([f"id_{i}" for i in range(n)]),
            "v": rng.integers(0, 10, n).astype(np.int32)}, d, cfg, "s0")
        controller.upload_segment("t", d)
        out = controller.tune_table("t")
        assert "low" in out["indexing"].inverted_index_columns
        assert "high" in out["indexing"].bloom_filter_columns
        assert out["changes"]
        # persisted: registry carries the tuned config
        stored = registry.table_config("t_OFFLINE")
        assert "low" in stored.indexing.inverted_index_columns
        # idempotent second run
        again = controller.tune_table("t")
        assert again["changes"] == []
        server.stop()
