"""Minion task framework: mergeRollup / realtimeToOffline / purge.

Reference analogs: MergeRollupMinionClusterIntegrationTest,
RealtimeToOfflineSegmentsMinionClusterIntegrationTest,
PurgeMinionClusterIntegrationTest — segment counts drop, query results
stay identical, watermarks advance.
"""

import time

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.cluster.registry import ClusterRegistry, SegmentState
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import StreamConfig, TableConfig, TableType
from pinot_tpu.controller.controller import Controller
from pinot_tpu.minion.worker import MinionWorker
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.stream.memory_stream import TopicRegistry


def wait_until(cond, timeout=15.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def cluster(tmp_path):
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "deepstore"))
    servers = [
        ServerInstance(f"server_{i}", registry, str(tmp_path / f"srv{i}"),
                       device_executor=None)
        for i in range(2)
    ]
    for s in servers:
        s.start()
    broker = Broker(registry, timeout_s=10.0)
    minion = MinionWorker(registry, controller, str(tmp_path / "minion"))
    yield registry, controller, servers, broker, minion
    minion.stop()
    broker.close()
    for s in servers:
        try:
            s.stop()
        except Exception:
            pass


def _sales_table(tmp_path, controller, task_configs, n_segments=4, rows=500):
    schema = Schema.build(
        name="sales",
        dimensions=[("region", DataType.STRING), ("deleted", DataType.INT)],
        metrics=[("amount", DataType.INT)],
    )
    cfg = TableConfig(table_name="sales", replication=1,
                      task_configs=task_configs)
    controller.add_table(cfg, schema)
    rng = np.random.default_rng(17)
    for i in range(n_segments):
        cols = {
            "region": np.array(["na", "eu", "apac"])[rng.integers(0, 3, rows)],
            "deleted": (rng.random(rows) < 0.2).astype(np.int32),
            "amount": rng.integers(1, 100, rows).astype(np.int32),
        }
        d = str(tmp_path / f"up_{i}")
        build_segment(schema, cols, d, cfg, f"sales_s{i}")
        controller.upload_segment("sales", d)
    return schema, cfg


def _rows(broker, sql):
    r = broker.execute(sql)
    assert not r.get("exceptions"), r
    return r["resultTable"]["rows"]


class TestMergeRollup:
    def test_concat_merge_preserves_results(self, cluster, tmp_path):
        registry, controller, servers, broker, minion = cluster
        _sales_table(tmp_path, controller,
                     {"MergeRollupTask": {"max_docs_per_segment": 10_000}})
        assert wait_until(
            lambda: len(registry.external_view("sales_OFFLINE")) == 4)
        before = _rows(
            broker,
            "SELECT region, COUNT(*), SUM(amount) FROM sales "
            "GROUP BY region ORDER BY region",
        )

        ids = controller.run_task_generation()
        assert len(ids) == 1
        task = minion.run_one()
        assert task is not None and task["state"] == "DONE", task
        # inputs deleted, single merged segment remains
        segs = registry.segments("sales_OFFLINE")
        assert len(segs) == 1 and next(iter(segs)).startswith("merged_")
        assert wait_until(
            lambda: set(registry.external_view("sales_OFFLINE"))
            == set(segs))
        after = _rows(
            broker,
            "SELECT region, COUNT(*), SUM(amount) FROM sales "
            "GROUP BY region ORDER BY region",
        )
        assert after == before
        # re-generation finds nothing new to merge, and the completed
        # lineage entry is GC'd once servers stop serving the from-set
        assert wait_until(lambda: controller.run_task_generation() == []
                          and registry.lineage("sales_OFFLINE") == {})

    def test_rollup_mode_aggregates_duplicate_rows(self, cluster, tmp_path):
        registry, controller, servers, broker, minion = cluster
        schema = Schema.build(
            name="traffic",
            dimensions=[("site", DataType.STRING)],
            metrics=[("hits", DataType.LONG)],
        )
        cfg = TableConfig(
            table_name="traffic", replication=1,
            task_configs={"MergeRollupTask": {
                "mode": "rollup", "rollup_aggregates": {"hits": "SUM"},
            }},
        )
        controller.add_table(cfg, schema)
        for i in range(3):
            cols = {"site": ["a", "b", "a"], "hits": [1, 10, 100]}
            d = str(tmp_path / f"tr_{i}")
            build_segment(schema, cols, d, cfg, f"traffic_s{i}")
            controller.upload_segment("traffic", d)
        assert wait_until(
            lambda: len(registry.external_view("traffic_OFFLINE")) == 3)
        controller.run_task_generation()
        task = minion.run_one()
        assert task["state"] == "DONE", task
        segs = registry.segments("traffic_OFFLINE")
        assert len(segs) == 1
        # rollup collapsed 9 rows to 2 groups; sums preserved
        assert next(iter(segs.values())).n_docs == 2
        assert wait_until(
            lambda: set(registry.external_view("traffic_OFFLINE")) == set(segs))
        rows = _rows(broker,
                     "SELECT site, SUM(hits) FROM traffic GROUP BY site ORDER BY site")
        assert rows == [["a", 303], ["b", 30]]

    def test_merge_preserves_null_vectors(self, cluster, tmp_path):
        """Nullness lives in per-column null vectors, not the forward index;
        a rebuild that dropped them would silently un-null rows."""
        registry, controller, servers, broker, minion = cluster
        schema = Schema.build(
            name="nv",
            dimensions=[("k", DataType.STRING)],
            metrics=[("v", DataType.INT)],
        )
        cfg = TableConfig(table_name="nv", replication=1,
                          task_configs={"MergeRollupTask": {}})
        controller.add_table(cfg, schema)
        from pinot_tpu.storage.creator import build_segment as _bs

        for i in range(2):
            _bs(schema, {"k": ["a", None, "b"], "v": [1, None, 3]},
                str(tmp_path / f"nv{i}"), cfg, f"nv_{i}")
            controller.upload_segment("nv", str(tmp_path / f"nv{i}"))
        assert wait_until(
            lambda: len(registry.external_view("nv_OFFLINE")) == 2)
        assert _rows(broker, "SELECT COUNT(*) FROM nv WHERE k IS NULL") == [[2]]
        controller.run_task_generation()
        task = minion.run_one()
        assert task["state"] == "DONE", task
        segs = registry.segments("nv_OFFLINE")
        assert len(segs) == 1
        assert wait_until(
            lambda: set(registry.external_view("nv_OFFLINE")) == set(segs))
        assert _rows(broker, "SELECT COUNT(*) FROM nv WHERE k IS NULL") == [[2]]
        assert _rows(broker, "SELECT COUNT(*) FROM nv WHERE v IS NOT NULL") == [[4]]

    def test_worker_thread_drains_queue(self, cluster, tmp_path):
        registry, controller, servers, broker, minion = cluster
        _sales_table(tmp_path, controller,
                     {"MergeRollupTask": {"max_docs_per_segment": 1_100}},
                     n_segments=4, rows=500)
        assert wait_until(
            lambda: len(registry.external_view("sales_OFFLINE")) == 4)
        minion.start()
        ids = controller.run_task_generation()
        assert len(ids) == 2  # 2 buckets of 2x500 docs under the 1100 cap
        assert wait_until(lambda: all(
            t["state"] == "DONE"
            for t in registry.tasks(table="sales_OFFLINE")), timeout=30)
        assert len(registry.segments("sales_OFFLINE")) == 2
        assert _rows(broker, "SELECT COUNT(*) FROM sales") == [[2000]]


class TestRepair:
    def test_dead_minion_task_requeued_and_lineage_unwound(self, cluster, tmp_path):
        """A minion that dies mid-task must not wedge the table: its RUNNING
        claim requeues, and a mid-swap IN_PROGRESS lineage (with the
        replacement already uploaded) unwinds without double-routing."""
        registry, controller, servers, broker, minion = cluster
        _sales_table(tmp_path, controller,
                     {"MergeRollupTask": {"max_docs_per_segment": 10_000}})
        assert wait_until(
            lambda: len(registry.external_view("sales_OFFLINE")) == 4)
        before = _rows(broker, "SELECT COUNT(*), SUM(amount) FROM sales")

        ids = controller.run_task_generation()
        # a "dead" minion claims the task and vanishes
        claimed = registry.claim_task("minion_dead")
        assert claimed is not None and claimed["id"] == ids[0]
        # ... after having started the lineage swap and uploaded the merge
        import numpy as np

        from pinot_tpu.storage.creator import build_segment as _bs

        schema = registry.table_schema("sales_OFFLINE")
        cols = {"region": np.array(["na"] * 10), "deleted": np.zeros(10, np.int32),
                "amount": np.ones(10, np.int32)}
        d = str(tmp_path / "half_merged")
        _bs(schema, cols, d, registry.table_config("sales_OFFLINE"), "half_merged")
        lid = registry.start_lineage(
            "sales_OFFLINE", claimed["config"]["segments"], ["half_merged"])
        controller.upload_segment("sales_OFFLINE", d)
        # the half-finished replacement must be invisible to queries
        assert _rows(broker, "SELECT COUNT(*), SUM(amount) FROM sales") == before

        rep = controller.run_task_repair(stale_ms=0)
        assert rep["requeued_tasks"] and rep["reverted_lineage"]
        assert "half_merged" not in registry.segments("sales_OFFLINE")
        assert registry.lineage("sales_OFFLINE") == {}
        # a live minion picks the requeued task up and finishes the job
        task = minion.run_one()
        assert task is not None and task["state"] == "DONE", task
        segs = registry.segments("sales_OFFLINE")
        assert len(segs) == 1
        assert wait_until(
            lambda: set(registry.external_view("sales_OFFLINE")) == set(segs))
        assert _rows(broker, "SELECT COUNT(*), SUM(amount) FROM sales") == before


class TestPurge:
    def test_purge_drops_matching_rows(self, cluster, tmp_path):
        registry, controller, servers, broker, minion = cluster
        _sales_table(tmp_path, controller,
                     {"PurgeTask": {"filter": "deleted = 1"}})
        assert wait_until(
            lambda: len(registry.external_view("sales_OFFLINE")) == 4)
        keep = _rows(broker,
                     "SELECT COUNT(*), SUM(amount) FROM sales WHERE deleted = 0")
        ids = controller.run_task_generation()
        assert len(ids) == 1
        task = minion.run_one()
        assert task["state"] == "DONE", task
        segs = registry.segments("sales_OFFLINE")
        assert wait_until(
            lambda: set(registry.external_view("sales_OFFLINE")) == set(segs))
        assert _rows(broker, "SELECT COUNT(*), SUM(amount) FROM sales") == keep
        assert _rows(broker,
                     "SELECT COUNT(*) FROM sales WHERE deleted = 1") == [[0]]
        # purged markers recorded: nothing new generated
        assert controller.run_task_generation() == []


class TestRealtimeToOffline:
    def test_moves_window_and_advances_watermark(self, cluster, tmp_path):
        registry, controller, servers, broker, minion = cluster
        TopicRegistry.delete("events")
        topic = TopicRegistry.create("events", 1)
        schema = Schema.build(
            name="events",
            dimensions=[("kind", DataType.STRING)],
            metrics=[("v", DataType.INT)],
            datetimes=[("ts", DataType.LONG)],
        )
        off_cfg = TableConfig(table_name="events", time_column="ts")
        controller.add_table(off_cfg, schema)
        rt_cfg = TableConfig(
            table_name="events", table_type=TableType.REALTIME,
            time_column="ts",
            stream=StreamConfig(
                stream_type="memory", topic="events", decoder="json",
                segment_flush_threshold_rows=50,
                segment_flush_threshold_seconds=3600,
            ),
            task_configs={"RealtimeToOfflineSegmentsTask": {
                "bucket_ms": 1000, "buffer_ms": 0,
            }},
        )
        controller.add_table(rt_cfg, schema)
        # buckets: ts 0..99, 1000..1099, 2000..2049 (a single consume batch
        # may seal them all into one segment — the window extract handles it)
        for ts in (list(range(100)) + list(range(1000, 1100))
                   + list(range(2000, 2050))):
            topic.publish_json({"kind": f"k{ts % 3}", "v": 1, "ts": ts})
        assert wait_until(lambda: any(
            r.state == SegmentState.ONLINE
            for r in registry.segments("events_REALTIME").values()), timeout=20)
        assert wait_until(lambda: _rows(
            broker, "SELECT COUNT(*) FROM events") == [[250]])

        ids = controller.run_task_generation(now_ms=10_000)
        assert len(ids) == 1
        task = minion.run_one()
        assert task["state"] == "DONE", task
        # offline table received the bucket-0 rows
        off_segs = registry.segments("events_OFFLINE")
        assert len(off_segs) == 1
        assert next(iter(off_segs.values())).n_docs == 100
        meta = registry.task_metadata_get(
            "events_REALTIME", "RealtimeToOfflineSegmentsTask")
        assert meta["watermark_ms"] == 1000
        # hybrid query still sees every row exactly once
        assert wait_until(
            lambda: len(registry.external_view("events_OFFLINE")) == 1)
        assert _rows(broker, "SELECT COUNT(*) FROM events") == [[250]]

        # next generation targets bucket 1 (bucket 2 stays: no data past it)
        ids = controller.run_task_generation(now_ms=10_000)
        assert len(ids) == 1
        task = minion.run_one()
        assert task["state"] == "DONE", task
        assert registry.task_metadata_get(
            "events_REALTIME", "RealtimeToOfflineSegmentsTask"
        )["watermark_ms"] == 2000
        assert wait_until(
            lambda: len(registry.external_view("events_OFFLINE")) == 2)
        assert _rows(broker, "SELECT COUNT(*) FROM events") == [[250]]
        rows = _rows(broker,
                     "SELECT kind, COUNT(*) FROM events GROUP BY kind ORDER BY kind")
        assert [r[1] for r in rows] == [84, 83, 83]


class TestRefreshSegments:
    """RefreshSegmentsTask: segments rebuild under the CURRENT
    IndexingConfig after a config change (the reference's reload story)."""

    def test_index_config_change_triggers_rebuild(self, cluster, tmp_path):
        from pinot_tpu.common.table_config import IndexingConfig
        from pinot_tpu.storage.segment import ImmutableSegment

        registry, controller, servers, broker, minion = cluster
        schema, cfg = _sales_table(
            tmp_path, controller, {"RefreshSegmentsTask": {}}, n_segments=2)
        assert wait_until(
            lambda: len(registry.external_view("sales_OFFLINE")) == 2)
        before = _rows(broker, "SELECT region, SUM(amount) FROM sales "
                               "GROUP BY region ORDER BY region")

        # no mismatch yet: generation is a no-op
        assert controller.run_task_generation() == []

        # add an inverted index + bloom to the table config
        cfg2 = TableConfig(
            table_name="sales", replication=1,
            task_configs={"RefreshSegmentsTask": {}},
            indexing=IndexingConfig(inverted_index_columns=["region"],
                                    bloom_filter_columns=["region"]))
        controller.add_table(cfg2, schema)
        ids = controller.run_task_generation()
        assert len(ids) == 1
        minion.start()
        assert wait_until(lambda: all(
            t["state"] == "DONE" for t in registry.tasks(table="sales_OFFLINE")
            if t["type"] == "RefreshSegmentsTask"), timeout=30)

        # swapped segments carry the new indexes; results unchanged
        def refreshed():
            recs = registry.segments("sales_OFFLINE")
            return [r for r in recs.values() if r.name.startswith("refreshed_")]

        assert wait_until(lambda: len(refreshed()) == 2, timeout=30)
        for rec in refreshed():
            seg = ImmutableSegment(rec.location)
            assert seg.column_metadata("region").has_inverted
            assert seg.column_metadata("region").has_bloom
        assert wait_until(lambda: _rows(
            broker, "SELECT region, SUM(amount) FROM sales "
                    "GROUP BY region ORDER BY region") == before, timeout=30)

        # steady state: no further refresh tasks get generated
        registry.prune_terminal_tasks(ttl_ms=0)
        assert wait_until(
            lambda: controller.run_task_generation() == [], timeout=30)

    def test_unachievable_index_config_does_not_loop(self, cluster, tmp_path):
        """An index the builder can't create (inverted on a RAW no-dict
        column) must not flag forever (r3 review: infinite rebuild loop)."""
        from pinot_tpu.common.table_config import IndexingConfig

        registry, controller, servers, broker, minion = cluster
        schema, cfg = _sales_table(
            tmp_path, controller, {"RefreshSegmentsTask": {}}, n_segments=1)
        cfg2 = TableConfig(
            table_name="sales", replication=1,
            task_configs={"RefreshSegmentsTask": {}},
            indexing=IndexingConfig(
                no_dictionary_columns=["amount"],
                inverted_index_columns=["amount"]))  # RAW: unbuildable
        controller.add_table(cfg2, schema)
        assert controller.run_task_generation() == []
