"""Controller HA: lease-based leader election + lead-controller
partitioning over the FileRegistry.

Reference: N controllers with Helix leader election + per-table lead
partitioning (pinot-controller/.../LeadControllerManager.java:1). The
VERDICT r4 scenario: the lead controller dies MID-CONSUME; a standby
promotes on lease expiry; the next segment still commits; broker/server
sessions survive the failover.
"""

import time

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.cluster.registry import FileRegistry, Role, SegmentState
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import StreamConfig, TableConfig, TableType
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.stream.memory_stream import TopicRegistry


def wait_until(cond, timeout=12.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


def test_lease_acquire_renew_expire(tmp_path):
    reg = FileRegistry(str(tmp_path / "reg"))
    a = reg.try_acquire_lease("x", "A", 400)
    assert a["holder"] == "A"
    # B cannot steal an unexpired lease
    assert reg.try_acquire_lease("x", "B", 400)["holder"] == "A"
    # A renews (expiry extends)
    a2 = reg.try_acquire_lease("x", "A", 400)
    assert a2["holder"] == "A" and a2["expires_ms"] >= a["expires_ms"]
    time.sleep(0.5)
    # expired: B takes it
    assert reg.try_acquire_lease("x", "B", 400)["holder"] == "B"
    assert reg.lease_holder("x") == "B"
    # voluntary release frees it immediately
    reg.release_lease("x", "B")
    assert reg.lease_holder("x") is None
    # release by a non-holder is a no-op
    reg.try_acquire_lease("x", "A", 400)
    reg.release_lease("x", "B")
    assert reg.lease_holder("x") == "A"


def test_partition_split_and_clean_handover(tmp_path):
    """Two LIVE controllers split the lead partitions (fair-share quota,
    not a monopoly); a clean shutdown hands the rest over without waiting
    out the TTL."""
    reg = FileRegistry(str(tmp_path / "reg"))
    a = Controller(reg, str(tmp_path / "dsA"), controller_id="ctrl_a")
    b = Controller(reg, str(tmp_path / "dsB"), controller_id="ctrl_b")
    a.start_ha(lease_ttl_ms=1200, interval_s=0.1)
    b.start_ha(lease_ttl_ms=1200, interval_s=0.1)
    everything = set(range(Controller.LEAD_PARTITIONS))

    def split_evenly():
        return (a._held_partitions | b._held_partitions == everything
                and not (a._held_partitions & b._held_partitions)
                and len(a._held_partitions) == len(b._held_partitions) == 2)

    assert wait_until(split_evenly, timeout=3), (
        a._held_partitions, b._held_partitions)
    # every table has exactly ONE lead
    for t in ("t1", "t2", "some_table_REALTIME"):
        assert a.is_lead_for(t) != b.is_lead_for(t)
    a.stop_ha(release=True)  # clean handover: leases released, not expired
    assert wait_until(lambda: b._held_partitions == everything, timeout=3)
    assert b.is_lead_for("any_table")
    # the drained controller is a tombstone, NOT back to lead-everything
    # (split-brain guard): its duty loops skip every table
    assert not a.is_lead_for("any_table") and not a._leads_global()
    b.stop_ha()


def test_failover_mid_consume(tmp_path):
    """The full VERDICT scenario on a durable FileRegistry."""
    TopicRegistry.delete("ha_clicks")
    topic = TopicRegistry.create("ha_clicks", 1)
    reg = FileRegistry(str(tmp_path / "reg"))
    lead = Controller(reg, str(tmp_path / "ds"), controller_id="ctrl_lead")
    standby = Controller(reg, str(tmp_path / "ds"), controller_id="ctrl_standby")
    lead.start_ha(lease_ttl_ms=800, interval_s=0.1)
    standby.start_ha(lease_ttl_ms=800, interval_s=0.1)
    lead.start_periodic_tasks(interval_s=0.3)
    standby.start_periodic_tasks(interval_s=0.3)
    server = ServerInstance("srv0", reg, str(tmp_path / "srv0"),
                            device_executor=None)
    server.start()
    broker = Broker(reg, timeout_s=10.0)
    try:
        schema = Schema.build(name="ha_clicks",
                              dimensions=[("page", DataType.STRING)],
                              metrics=[("n", DataType.INT)])
        cfg = TableConfig(
            table_name="ha_clicks", table_type=TableType.REALTIME,
            stream=StreamConfig(
                stream_type="memory", topic="ha_clicks", decoder="json",
                segment_flush_threshold_rows=50,
                segment_flush_threshold_seconds=3600,
            ),
        )
        lead.add_table(cfg, schema)
        # live controllers split the partitions; exactly one leads the table
        assert wait_until(
            lambda: lead._held_partitions | standby._held_partitions
            == set(range(Controller.LEAD_PARTITIONS)), timeout=3)
        assert lead.is_lead_for("ha_clicks_REALTIME") \
            != standby.is_lead_for("ha_clicks_REALTIME")

        def publish(n0, n1):
            for i in range(n0, n1):
                topic.publish_json({"page": f"p{i % 4}", "n": 1}, partition=0)

        def broker_count():
            r = broker.execute("SELECT COUNT(*) FROM ha_clicks")
            return -1 if r.get("exceptions") else r["resultTable"]["rows"][0][0]

        def online_segments():
            return sum(1 for rec in reg.segments("ha_clicks_REALTIME").values()
                       if rec.state == SegmentState.ONLINE)

        # consume begins; one segment commits under the original lead
        publish(0, 80)
        assert wait_until(lambda: broker_count() == 80), broker_count()
        assert wait_until(lambda: online_segments() >= 1)

        # the lead crashes MID-CONSUME (no lease release, no cleanup)
        lead.stop_ha(release=False)
        lead.stop_periodic_tasks()

        # the standby absorbs every partition within ~one TTL
        assert wait_until(
            lambda: standby._held_partitions
            == set(range(Controller.LEAD_PARTITIONS)), timeout=5), \
            standby._held_partitions
        assert standby.is_lead_for("ha_clicks_REALTIME")

        # the NEXT segment still commits after the failover
        before = online_segments()
        publish(80, 200)
        assert wait_until(lambda: broker_count() == 200, timeout=15), \
            broker_count()
        assert wait_until(lambda: online_segments() > before, timeout=15)

        # broker + server sessions survived: full query path still green
        r = broker.execute("SELECT page, COUNT(*) FROM ha_clicks "
                           "GROUP BY page ORDER BY page")
        assert not r.get("exceptions"), r
        assert [row[1] for row in r["resultTable"]["rows"]] == [50] * 4

        # background duties run under the new lead (retention sweep works)
        assert standby.run_retention() == []
    finally:
        broker.close()
        server.stop()
        standby.stop_periodic_tasks()
        standby.stop_ha()
        TopicRegistry.delete("ha_clicks")


def test_duties_partition_between_live_controllers(tmp_path):
    """With HA on, a controller that leads NO partition of a table skips
    its background duties for it (lead-controller partitioning, not just
    failover)."""
    reg = FileRegistry(str(tmp_path / "reg"))
    a = Controller(reg, str(tmp_path / "dsA"), controller_id="ctrl_a")
    a.start_ha(lease_ttl_ms=2000, interval_s=0.2)
    # ctrl_b never ticks: it holds nothing, so its duty loops are no-ops
    b = Controller(reg, str(tmp_path / "dsB"), controller_id="ctrl_b")
    b._ha_thread = object()  # HA "on" without a tick loop → leads nothing
    try:
        schema = Schema.build(name="old", dimensions=[("k", DataType.STRING)],
                              metrics=[("v", DataType.INT)])
        a.add_table(TableConfig(table_name="old", retention_days=1), schema)
        import numpy as np

        from pinot_tpu.storage.creator import build_segment

        d = str(tmp_path / "seg")
        build_segment(schema, {"k": np.array(["x"]),
                               "v": np.array([1], dtype=np.int32)}, d,
                      segment_name="old_s0")
        # no servers: upload only records the segment + location
        reg.add_segment_record = getattr(reg, "add_segment_record", None)
        from pinot_tpu.cluster.registry import SegmentRecord

        reg.add_segment(SegmentRecord(
            name="old_s0", table="old_OFFLINE", n_docs=1, location=d,
            state=SegmentState.ONLINE, start_time=0, end_time=1), [])
        assert b.run_retention() == []  # not the lead: skips the table
        assert ("old_OFFLINE", "old_s0") in a.run_retention()
    finally:
        b._ha_thread = None
        a.stop_ha()


@pytest.mark.slow
def test_failover_across_os_processes(tmp_path):
    """Two controller PROCESSES contend over one FileRegistry; SIGKILL the
    lead; the standby absorbs every partition within ~one lease TTL (the
    closest analog to the reference's multi-JVM Helix leader election)."""
    import os
    import signal
    import subprocess
    import sys

    reg_path = str(tmp_path / "reg")
    child = (
        "import sys, time\n"
        f"sys.path.insert(0, {os.getcwd()!r})\n"
        "from pinot_tpu.cluster.registry import FileRegistry\n"
        "from pinot_tpu.controller.controller import Controller\n"
        f"reg = FileRegistry({reg_path!r})\n"
        f"c = Controller(reg, {str(tmp_path / 'ds')!r}, controller_id=sys.argv[1])\n"
        "c.start_ha(lease_ttl_ms=800, interval_s=0.1)\n"
        "while True:\n"
        "    time.sleep(1)\n"
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    lead = subprocess.Popen([sys.executable, "-c", child, "lead"],
                            stdout=subprocess.DEVNULL, env=env)
    standby = subprocess.Popen([sys.executable, "-c", child, "standby"],
                               stdout=subprocess.DEVNULL, env=env)
    try:
        # wait until the standby holds its fair share (both alive)
        reg = FileRegistry(reg_path)
        assert wait_until(
            lambda: reg.lease_holder("controller/lead/0") is not None,
            timeout=20)
        assert wait_until(lambda: any(
            reg.lease_holder(f"controller/lead/{p}") == "standby"
            for p in range(Controller.LEAD_PARTITIONS)), timeout=20)
        os.kill(lead.pid, signal.SIGKILL)  # hard crash: no lease release
        assert wait_until(lambda: all(
            reg.lease_holder(f"controller/lead/{p}") == "standby"
            for p in range(Controller.LEAD_PARTITIONS)), timeout=10), [
            reg.lease_holder(f"controller/lead/{p}")
            for p in range(Controller.LEAD_PARTITIONS)]
    finally:
        for p in (lead, standby):
            try:
                p.kill()
                p.wait(timeout=10)  # reap: no zombies across the session
            except Exception:
                pass
