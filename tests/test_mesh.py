"""Mesh-parallel combine tests on the virtual 8-device CPU mesh.

The multi-chip contract: sharding the segment axis over a Mesh and combining
accumulators with psum/pmin/pmax must give bit-identical results to the
single-device batched launch (the reference's equivalent guarantee is
combine-operator merge correctness, operator/combine/).
"""

import numpy as np
import pytest

import jax

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.engine.device import DeviceExecutor
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.parallel.mesh import make_mesh
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment


@pytest.fixture(scope="module")
def mesh_engines(tmp_path_factory):
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    rng = np.random.default_rng(23)
    n = 5000
    cols = {
        "k1": np.array([f"g{i}" for i in range(20)])[rng.integers(0, 20, n)],
        "k2": np.array(["x", "y"])[rng.integers(0, 2, n)],
        "v": rng.integers(0, 1000, n).astype(np.int32),
    }
    schema = Schema.build(
        name="m",
        dimensions=[("k1", DataType.STRING), ("k2", DataType.STRING)],
        metrics=[("v", DataType.INT)],
    )
    base = tmp_path_factory.mktemp("meshseg")
    mesh = make_mesh(8)
    sharded = QueryEngine(device_executor=DeviceExecutor(mesh=mesh))
    single = QueryEngine()
    # 6 segments of uneven sizes: exercises padding to the mesh multiple
    bounds = [0, 400, 1400, 2000, 3100, 4200, n]
    for i in range(6):
        part = {k: v[bounds[i]:bounds[i + 1]] for k, v in cols.items()}
        build_segment(schema, part, str(base / f"s{i}"), TableConfig(table_name="m"), f"s{i}")
        seg = ImmutableSegment(str(base / f"s{i}"))
        sharded.add_segment("m", seg)
        single.add_segment("m", seg)
    return sharded, single


MESH_QUERIES = [
    "SELECT COUNT(*) FROM m",
    "SELECT SUM(v), MIN(v), MAX(v), AVG(v) FROM m WHERE k2 = 'x'",
    "SELECT k1, COUNT(*), SUM(v) FROM m GROUP BY k1 ORDER BY k1 LIMIT 25",
    "SELECT k1, k2, MAX(v) FROM m WHERE v > 100 GROUP BY k1, k2 ORDER BY k1, k2 LIMIT 50",
    "SELECT DISTINCTCOUNT(k1) FROM m WHERE k2 = 'y'",
    "SELECT k2, DISTINCTCOUNTHLL(k1) FROM m GROUP BY k2 ORDER BY k2",
    "SELECT COUNT(*) FROM m WHERE k1 IN ('g1','g5') OR v BETWEEN 10 AND 50",
]


@pytest.mark.parametrize("sql", MESH_QUERIES)
def test_sharded_equals_single(mesh_engines, sql):
    sharded, single = mesh_engines
    rs = sharded.execute(sql)
    r1 = single.execute(sql)
    assert not rs.get("exceptions"), rs
    assert rs["resultTable"]["rows"] == r1["resultTable"]["rows"], (
        rs["resultTable"]["rows"][:4],
        r1["resultTable"]["rows"][:4],
    )
    assert rs["numDocsScanned"] == r1["numDocsScanned"]


def test_sharded_uses_device(mesh_engines):
    sharded, _ = mesh_engines
    sharded.execute("SELECT k1, SUM(v) FROM m GROUP BY k1")
    assert len(sharded.device._pipelines) > 0


class TestSortedRegimeMesh:
    """High-cardinality (radix) regime ON the mesh: per-shard group tables
    are KEYED, so parallel/mesh.py merges them by key (merge_tables) —
    the shape that used to route every multi-chip high-card query to the
    host. Sharded == single-device == host, exactly."""

    @pytest.fixture(scope="class")
    def hc_engines(self, tmp_path_factory):
        rng = np.random.default_rng(37)
        n, U, I = 12_000, 2300, 2000  # 4.6M key space > MAX_DENSE_GROUPS
        # pin both dictionaries at full cardinality, then draw ~3k extra
        # distinct pairs; groups deliberately SPAN segments so the merge
        # must combine cross-shard partials
        u = rng.integers(0, U, n).astype(np.int32)
        i = rng.integers(0, I, n).astype(np.int32)
        u[:U] = np.arange(U, dtype=np.int32)
        i[:I] = np.arange(I, dtype=np.int32)
        cols = {
            "u": u, "i": i,
            "v": rng.integers(-500, 500, n).astype(np.int64),
        }
        schema = Schema.build(
            name="hcm",
            dimensions=[("u", DataType.INT), ("i", DataType.INT)],
            metrics=[("v", DataType.LONG)],
        )
        base = tmp_path_factory.mktemp("hcmesh")
        sharded = QueryEngine(device_executor=DeviceExecutor(mesh=make_mesh(8)))
        single = QueryEngine()
        host = QueryEngine(device_executor=None)
        bounds = [0, 1500, 2600, 4800, 6400, 9000, n]  # mesh-unaligned
        for s in range(6):
            part = {k: v[bounds[s]:bounds[s + 1]] for k, v in cols.items()}
            build_segment(schema, part, str(base / f"s{s}"),
                          TableConfig(table_name="hcm"), f"s{s}")
            seg = ImmutableSegment(str(base / f"s{s}"))
            for eng in (sharded, single, host):
                eng.add_segment("hcm", seg)
        return sharded, single, host

    @pytest.mark.parametrize("sql", [
        "SELECT u, i, COUNT(*), SUM(v) FROM hcm GROUP BY u, i "
        "ORDER BY COUNT(*) DESC, u, i LIMIT 30",
        "SELECT u, i, MIN(v), MAX(v), AVG(v) FROM hcm WHERE v > -200 "
        "GROUP BY u, i ORDER BY MIN(v), u, i LIMIT 40",
    ])
    def test_mesh_equals_single_equals_host(self, hc_engines, sql):
        sharded, single, host = hc_engines
        rs, r1, rh = (e.execute(sql) for e in (sharded, single, host))
        for r in (rs, r1, rh):
            assert not r.get("exceptions"), r
        assert rs["resultTable"]["rows"] == r1["resultTable"]["rows"]
        assert rs["resultTable"]["rows"] == rh["resultTable"]["rows"]

    def test_mesh_sorted_template_on_device(self, hc_engines):
        sharded, _, _ = hc_engines
        sharded.execute("SELECT u, i, SUM(v) FROM hcm GROUP BY u, i")
        shapes = {t[0] for (t, _m, _bs, _w, _tr, _pl) in sharded.device._pipelines}
        assert "groupby_sorted" in shapes

    def test_mesh_overflow_still_falls_back(self, hc_engines):
        """Distinct > sorted_k under the mesh: merged n_groups_total must
        trip the SAME host fallback as single-device."""
        sharded, _, host = hc_engines
        small = QueryEngine(
            device_executor=DeviceExecutor(mesh=make_mesh(8),
                                           num_groups_limit=1000),
            num_groups_limit=1000)
        host_small = QueryEngine(device_executor=None, num_groups_limit=1000)
        for seg in sharded.tables["hcm"].segments.values():
            small.add_segment("hcm", seg)
            host_small.add_segment("hcm", seg)
        sql = ("SELECT u, i, SUM(v) FROM hcm GROUP BY u, i "
               "ORDER BY u, i LIMIT 20")
        rs, rh = small.execute(sql), host_small.execute(sql)
        assert not rs.get("exceptions"), rs
        assert rs["resultTable"]["rows"] == rh["resultTable"]["rows"]
