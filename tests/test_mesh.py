"""Mesh-parallel combine tests on the virtual 8-device CPU mesh.

The multi-chip contract: sharding the segment axis over a Mesh and combining
accumulators with psum/pmin/pmax must give bit-identical results to the
single-device batched launch (the reference's equivalent guarantee is
combine-operator merge correctness, operator/combine/).
"""

import numpy as np
import pytest

import jax

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.engine.device import DeviceExecutor
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.parallel.mesh import make_mesh
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment


@pytest.fixture(scope="module")
def mesh_engines(tmp_path_factory):
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    rng = np.random.default_rng(23)
    n = 5000
    cols = {
        "k1": np.array([f"g{i}" for i in range(20)])[rng.integers(0, 20, n)],
        "k2": np.array(["x", "y"])[rng.integers(0, 2, n)],
        "v": rng.integers(0, 1000, n).astype(np.int32),
    }
    schema = Schema.build(
        name="m",
        dimensions=[("k1", DataType.STRING), ("k2", DataType.STRING)],
        metrics=[("v", DataType.INT)],
    )
    base = tmp_path_factory.mktemp("meshseg")
    mesh = make_mesh(8)
    sharded = QueryEngine(device_executor=DeviceExecutor(mesh=mesh))
    single = QueryEngine()
    # 6 segments of uneven sizes: exercises padding to the mesh multiple
    bounds = [0, 400, 1400, 2000, 3100, 4200, n]
    for i in range(6):
        part = {k: v[bounds[i]:bounds[i + 1]] for k, v in cols.items()}
        build_segment(schema, part, str(base / f"s{i}"), TableConfig(table_name="m"), f"s{i}")
        seg = ImmutableSegment(str(base / f"s{i}"))
        sharded.add_segment("m", seg)
        single.add_segment("m", seg)
    return sharded, single


MESH_QUERIES = [
    "SELECT COUNT(*) FROM m",
    "SELECT SUM(v), MIN(v), MAX(v), AVG(v) FROM m WHERE k2 = 'x'",
    "SELECT k1, COUNT(*), SUM(v) FROM m GROUP BY k1 ORDER BY k1 LIMIT 25",
    "SELECT k1, k2, MAX(v) FROM m WHERE v > 100 GROUP BY k1, k2 ORDER BY k1, k2 LIMIT 50",
    "SELECT DISTINCTCOUNT(k1) FROM m WHERE k2 = 'y'",
    "SELECT k2, DISTINCTCOUNTHLL(k1) FROM m GROUP BY k2 ORDER BY k2",
    "SELECT COUNT(*) FROM m WHERE k1 IN ('g1','g5') OR v BETWEEN 10 AND 50",
]


@pytest.mark.parametrize("sql", MESH_QUERIES)
def test_sharded_equals_single(mesh_engines, sql):
    sharded, single = mesh_engines
    rs = sharded.execute(sql)
    r1 = single.execute(sql)
    assert not rs.get("exceptions"), rs
    assert rs["resultTable"]["rows"] == r1["resultTable"]["rows"], (
        rs["resultTable"]["rows"][:4],
        r1["resultTable"]["rows"][:4],
    )
    assert rs["numDocsScanned"] == r1["numDocsScanned"]


def test_sharded_uses_device(mesh_engines):
    sharded, _ = mesh_engines
    sharded.execute("SELECT k1, SUM(v) FROM m GROUP BY k1")
    assert len(sharded.device._pipelines) > 0
