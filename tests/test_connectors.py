"""DataFrame connectors (spark read / flink sink roles)."""

import time

import numpy as np
import pandas as pd
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.connectors import query_df, read_table, write_table
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance


@pytest.fixture()
def cluster(tmp_path):
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "ds"))
    server = ServerInstance("s0", registry, str(tmp_path / "srv"),
                            device_executor=None)
    server.start()
    broker = Broker(registry)
    yield registry, controller, broker
    broker.close()
    server.stop()


def _wait_count(broker, table, want, timeout=12):
    deadline = time.time() + timeout
    while time.time() < deadline:
        r = broker.execute(f"SELECT COUNT(*) FROM {table}")
        if not r.get("exceptions") and r["resultTable"]["rows"][0][0] == want:
            return True
        time.sleep(0.1)
    return False


def test_write_then_read_roundtrip(cluster):
    registry, controller, broker = cluster
    schema = Schema.build(name="sales",
                          dimensions=[("region", DataType.STRING)],
                          metrics=[("amt", DataType.LONG)])
    controller.add_table(TableConfig(table_name="sales"), schema)
    rng = np.random.default_rng(6)
    df = pd.DataFrame({
        "region": np.array(["na", "eu", "ap"])[rng.integers(0, 3, 25_000)],
        "amt": rng.integers(0, 1000, 25_000).astype(np.int64),
    })
    names = write_table(df, schema, "sales", controller, segment_rows=10_000)
    assert len(names) == 3  # 25k rows / 10k per segment
    assert _wait_count(broker, "sales", 25_000)

    # aggregate query → DataFrame
    g = query_df(broker, "SELECT region, SUM(amt) FROM sales "
                         "GROUP BY region ORDER BY region")
    want = df.groupby("region").amt.sum()
    assert list(g.iloc[:, 0]) == ["ap", "eu", "na"]
    for _, row in g.iterrows():
        assert row.iloc[1] == float(want[row.iloc[0]])

    # paged full-table read returns every row
    back = read_table(broker, "sales", batch_rows=7_000)
    assert len(back) == 25_000
    assert back["amt"].sum() == df["amt"].sum()
    assert sorted(back["region"].unique()) == ["ap", "eu", "na"]

    # filtered + projected read
    na = read_table(broker, "sales", columns=["amt"],
                    where="region = 'na'", batch_rows=9_999)
    assert len(na) == int((df.region == "na").sum())
    assert na["amt"].sum() == int(df[df.region == "na"].amt.sum())


def test_query_df_error_surfaces(cluster):
    registry, controller, broker = cluster
    with pytest.raises(RuntimeError, match="query failed"):
        query_df(broker, "SELECT * FROM does_not_exist")


def test_read_table_quotes_segment_names(cluster):
    """A segment name containing a single quote must round-trip: read_table
    interpolates it as a SQL literal, which needs '' escaping (advisor
    finding: string-built SQL broke on quoted identifiers/literals)."""
    registry, controller, broker = cluster
    schema = Schema.build(name="qt",
                          dimensions=[("k", DataType.STRING)],
                          metrics=[("v", DataType.LONG)])
    controller.add_table(TableConfig(table_name="qt"), schema)
    df = pd.DataFrame({"k": ["a", "b"] * 50, "v": np.arange(100, dtype=np.int64)})
    names = write_table(df, schema, "qt", controller,
                        segment_prefix="o'brien")
    assert any("'" in n for n in names)
    assert _wait_count(broker, "qt", 100)
    back = read_table(broker, "qt", batch_rows=30)
    assert len(back) == 100
    assert back["v"].sum() == df["v"].sum()
