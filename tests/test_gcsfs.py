"""GCS PinotFS plugin against a faked google-cloud-storage (pinot-gcs
analog): segment lifecycle + gating error without the SDK."""

import sys
import types

import pytest

_STORE: dict = {}  # (bucket, name) -> bytes


class _FakeBlob:
    def __init__(self, bucket, name):
        self.bucket = bucket
        self.name = name

    def exists(self, client=None):
        return (self.bucket, self.name) in _STORE

    def upload_from_filename(self, filename):
        with open(filename, "rb") as f:
            _STORE[(self.bucket, self.name)] = f.read()

    def download_to_filename(self, filename):
        with open(filename, "wb") as f:
            f.write(_STORE[(self.bucket, self.name)])

    def delete(self):
        if (self.bucket, self.name) not in _STORE:
            raise _FakeNotFound(f"404 blob {self.name} not found")
        del _STORE[(self.bucket, self.name)]


class _FakeBucket:
    def __init__(self, name):
        self.name = name

    def blob(self, name):
        return _FakeBlob(self.name, name)

    def copy_blob(self, blob, dst_bucket, new_name):
        _STORE[(dst_bucket.name, new_name)] = _STORE[(blob.bucket, blob.name)]


class _FakeNotFound(Exception):
    pass


_FakeNotFound.__name__ = "NotFound"


class _FakeClient:
    def bucket(self, name):
        return _FakeBucket(name)

    def batch(self):
        import contextlib

        @contextlib.contextmanager
        def _b():
            yield  # deletes inside apply immediately; NotFound propagates

        return _b()

    def list_blobs(self, bucket_name, prefix="", max_results=None):
        blobs = [_FakeBlob(bucket_name, n)
                 for (b, n) in sorted(_STORE) if b == bucket_name
                 and n.startswith(prefix)]
        return blobs[:max_results] if max_results else blobs


@pytest.fixture()
def fake_gcs(monkeypatch):
    storage_mod = types.ModuleType("google.cloud.storage")
    storage_mod.Client = _FakeClient
    cloud_mod = types.ModuleType("google.cloud")
    cloud_mod.storage = storage_mod
    google_mod = types.ModuleType("google")
    google_mod.cloud = cloud_mod
    monkeypatch.setitem(sys.modules, "google", google_mod)
    monkeypatch.setitem(sys.modules, "google.cloud", cloud_mod)
    monkeypatch.setitem(sys.modules, "google.cloud.storage", storage_mod)
    _STORE.clear()
    yield
    _STORE.clear()


class TestGcsFS:
    def test_gating_error_without_sdk(self, monkeypatch):
        monkeypatch.setitem(sys.modules, "google", None)
        monkeypatch.setitem(sys.modules, "google.cloud", None)
        from pinot_tpu.storage.gcsfs import GcsFS

        with pytest.raises(RuntimeError, match="google-cloud-storage"):
            GcsFS()

    def test_scheme_registered(self, fake_gcs):
        from pinot_tpu.storage.fs import create_fs

        assert type(create_fs("gs://bucket/x")).__name__ == "GcsFS"

    def test_segment_lifecycle_and_sibling_isolation(self, fake_gcs, tmp_path):
        from pinot_tpu.storage.gcsfs import GcsFS

        a = tmp_path / "seg_1"
        b = tmp_path / "seg_10"
        (a / "sub").mkdir(parents=True)
        b.mkdir()
        (a / "m.json").write_text("{}")
        (a / "sub" / "x.bin").write_bytes(b"X")
        (b / "b.bin").write_bytes(b"B")

        fs = GcsFS()
        fs.copy(str(a), "gs://bkt/t/seg_1")
        fs.copy(str(b), "gs://bkt/t/seg_10")
        assert fs.list_files("gs://bkt/t") == ["seg_1", "seg_10"]

        d = tmp_path / "dl"
        fs.copy("gs://bkt/t/seg_1", str(d))
        assert (d / "m.json").read_text() == "{}"
        assert (d / "sub" / "x.bin").read_bytes() == b"X"

        fs.delete("gs://bkt/t/seg_1")
        assert not fs.exists("gs://bkt/t/seg_1")
        assert fs.exists("gs://bkt/t/seg_10")

    def test_remote_copy_and_racing_delete(self, fake_gcs, tmp_path):
        from pinot_tpu.storage.gcsfs import GcsFS

        src = tmp_path / "seg"
        src.mkdir()
        (src / "a.bin").write_bytes(b"A")
        fs = GcsFS()
        fs.copy(str(src), "gs://bkt/t/seg")
        # remote gs:// -> gs:// copy (tier move)
        fs.copy("gs://bkt/t/seg", "gs://bkt/cold/seg")
        d = tmp_path / "dl"
        fs.copy("gs://bkt/cold/seg", str(d))
        assert (d / "a.bin").read_bytes() == b"A"
        # racing delete: a STALE listing hitting already-gone objects must
        # be tolerated (S3's delete_objects is idempotent; GCS must match)
        _STORE.pop(("bkt", "t/seg/a.bin"))
        fs._delete_objs("bkt", ["t/seg/a.bin"])  # NotFound mid-batch: ok

    def test_repush_replaces(self, fake_gcs, tmp_path):
        from pinot_tpu.storage.gcsfs import GcsFS

        v1 = tmp_path / "v1"; v1.mkdir()
        (v1 / "old.bin").write_bytes(b"1")
        v2 = tmp_path / "v2"; v2.mkdir()
        (v2 / "new.bin").write_bytes(b"2")
        fs = GcsFS()
        fs.copy(str(v1), "gs://bkt/t/seg")
        fs.copy(str(v2), "gs://bkt/t/seg")
        d = tmp_path / "dl"
        fs.copy("gs://bkt/t/seg", str(d))
        assert (d / "new.bin").exists() and not (d / "old.bin").exists()
