"""Geospatial functions (geospatial/transform/function/ analogs)."""

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.ops.geo import haversine_m, parse_polygon, st_contains, st_point
from pinot_tpu.storage.creator import build_segment

CITIES = {
    "sf": (-122.4194, 37.7749),
    "oak": (-122.2712, 37.8044),
    "la": (-118.2437, 34.0522),
    "nyc": (-74.0060, 40.7128),
}


class TestGeoPrimitives:
    def test_haversine_known_distance(self):
        # SF -> LA ~ 559 km
        d = haversine_m(*CITIES["sf"][::-1][::-1], *CITIES["la"])
        d = haversine_m(CITIES["sf"][0], CITIES["sf"][1],
                        CITIES["la"][0], CITIES["la"][1])
        assert 545_000 < float(d) < 575_000

    def test_point_roundtrip(self):
        w = st_point(np.array([-122.4194]), np.array([37.7749]))
        from pinot_tpu.ops.geo import parse_points

        lon, lat = parse_points(w)
        assert abs(lon[0] + 122.4194) < 1e-6 and abs(lat[0] - 37.7749) < 1e-6

    def test_polygon_contains(self):
        bay = "POLYGON ((-123 37, -121.5 37, -121.5 38.5, -123 38.5, -123 37))"
        pts = st_point(np.array([CITIES["sf"][0], CITIES["la"][0]]),
                       np.array([CITIES["sf"][1], CITIES["la"][1]]))
        inside = st_contains(bay, pts)
        assert inside.tolist() == [True, False]

    def test_bad_polygon_raises(self):
        with pytest.raises(ValueError):
            parse_polygon("LINESTRING (0 0, 1 1)")

    def test_polygon_column_scalar_point_broadcast(self):
        # multi-row polygon column against one point must broadcast (r3)
        sq = "POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))"
        far = "POLYGON ((5 5, 6 5, 6 6, 5 6, 5 5))"
        out = st_contains(np.array([sq, far]), "POINT (0.5 0.5)")
        assert out.tolist() == [True, False]


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("geo")
    names = list(CITIES)
    lons = np.asarray([CITIES[c][0] for c in names])
    lats = np.asarray([CITIES[c][1] for c in names])
    schema = Schema.build(
        name="places",
        dimensions=[("city", DataType.STRING)],
        metrics=[("lon", DataType.DOUBLE), ("lat", DataType.DOUBLE)],
    )
    eng = QueryEngine(device_executor=None)
    seg = build_segment(schema, {"city": np.asarray(names), "lon": lons,
                                 "lat": lats},
                        str(tmp / "s"), TableConfig(table_name="places"), "s0")
    eng.add_segment("places", seg)
    return eng


class TestGeoQueries:
    def test_distance_filter(self, engine):
        # within 50km of SF: sf itself and oakland
        r = engine.execute(
            "SELECT city FROM places WHERE "
            "ST_DISTANCE(ST_POINT(lon, lat), "
            "ST_GEOGFROMTEXT('POINT (-122.4194 37.7749)')) < 50000 "
            "ORDER BY city")
        assert [x[0] for x in r["resultTable"]["rows"]] == ["oak", "sf"]

    def test_contains_filter(self, engine):
        r = engine.execute(
            "SELECT city FROM places WHERE "
            "ST_CONTAINS(ST_GEOGFROMTEXT('POLYGON ((-123 37, -121.5 37, "
            "-121.5 38.5, -123 38.5, -123 37))'), ST_POINT(lon, lat)) "
            "ORDER BY city")
        assert [x[0] for x in r["resultTable"]["rows"]] == ["oak", "sf"]

    def test_distance_in_select(self, engine):
        r = engine.execute(
            "SELECT city, ST_DISTANCE(ST_POINT(lon, lat), "
            "ST_GEOGFROMTEXT('POINT (-74.0060 40.7128)')) FROM places "
            "ORDER BY ST_DISTANCE(ST_POINT(lon, lat), "
            "ST_GEOGFROMTEXT('POINT (-74.0060 40.7128)')) LIMIT 1")
        assert r["resultTable"]["rows"][0][0] == "nyc"
        assert r["resultTable"]["rows"][0][1] < 1.0

    def test_st_within_and_astext(self, engine):
        r = engine.execute(
            "SELECT COUNT(*) FROM places WHERE "
            "ST_WITHIN(ST_POINT(lon, lat), "
            "ST_GEOGFROMTEXT('POLYGON ((-80 35, -70 35, -70 45, -80 45, -80 35))'))")
        assert r["resultTable"]["rows"][0][0] == 1  # nyc
        r = engine.execute(
            "SELECT ST_ASTEXT(ST_POINT(lon, lat)) FROM places "
            "WHERE city = 'nyc'")
        assert r["resultTable"]["rows"][0][0].startswith("POINT (")