"""Trigram regex-acceleration index (the reference FST index's role:
LuceneFSTIndexReader.java:1): LIKE/REGEXP_LIKE results must be identical
with and without the index, and the index must actually narrow the
candidate set at high cardinality."""

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.fstindex import TrigramIndex, required_literals
from pinot_tpu.storage.segment import ImmutableSegment


class TestRequiredLiterals:
    @pytest.mark.parametrize("pattern,want", [
        ("hello", ["hello"]),
        ("^abc.*xyz$", ["abc", "xyz"]),
        ("foo[0-9]+bar", ["foo", "bar"]),
        ("ab+cde", ["cde"]),          # adjacency breaks across +
        ("abc(def)?ghi", ["abc", "ghi"]),  # optional group not required
        ("abc(def)ghi", ["abc", "def", "ghi"]),
        ("a|b", []),                   # top-level alternation
        ("abc(x|y)def", ["abc", "def"]),
        ("ab", []),                    # too short for a trigram
        ("abc\\.def", ["abc.def"]),    # escaped metachar is literal
        ("abc\\d+def", ["abc", "def"]),
        ("colou?r", ["colo"]),         # 'u' optional; 'r' fragment too short
        ("(?i)abc", []),               # inline flags: bail conservatively
    ])
    def test_extraction(self, pattern, want):
        assert required_literals(pattern) == want

    def test_extraction_is_safe_on_random_patterns(self):
        """Whatever the analysis returns, every literal must be a true
        substring of every match (spot-checked via re on generated
        matches)."""
        import re

        cases = [
            ("user_[0-9]{3}@host", "user_123@host"),
            ("^prefix.*suffix$", "prefix--middle--suffix"),
            ("exact_string", "exact_string"),
            ("a(bc)+d", "abcbcd"),
        ]
        for pattern, example in cases:
            assert re.search(pattern, example)
            for lit in required_literals(pattern):
                assert lit in example, (pattern, lit, example)


class TestTrigramIndex:
    def test_candidates_narrow_and_verify(self):
        values = np.asarray(sorted(
            [f"user_{i:05d}@example.com" for i in range(5000)]
            + ["admin@root.sys", "zz_special_zz"]))
        idx = TrigramIndex.build(values)
        cand = idx.candidates("admin@root", len(values))
        assert cand is not None and len(cand) == 1
        assert values[cand[0]] == "admin@root.sys"
        # absent literal -> zero candidates without a single regex eval
        cand = idx.candidates("notpresentanywhere", len(values))
        assert cand is not None and len(cand) == 0
        # no usable literal -> None (caller scans)
        assert idx.candidates("a|b", len(values)) is None

    def test_save_load_roundtrip(self, tmp_path):
        values = np.asarray(["alpha", "beta", "gamma", "alphabet"])
        idx = TrigramIndex.build(values)
        idx.save(str(tmp_path), "c")
        idx2 = TrigramIndex.load(str(tmp_path), "c")
        got = idx2.candidates("alpha", len(values))
        assert sorted(np.asarray(values)[got].tolist()) == \
            ["alpha", "alphabet"]


@pytest.fixture(scope="module")
def engines(tmp_path_factory):
    rng = np.random.default_rng(44)
    n = 60_000
    hosts = np.asarray([f"h{i % 7}.dc{i % 3}.example" for i in range(40)])
    cols = {
        "url": np.asarray(
            [f"/api/v{rng.integers(1, 4)}/resource_{rng.integers(0, 3000):04d}"
             f"/{'edit' if rng.random() < 0.1 else 'view'}"
             for _ in range(n)]),
        "host": hosts[rng.integers(0, 40, n)],
        "v": rng.integers(0, 100, n).astype(np.int32),
    }
    schema = Schema.build(
        name="logs",
        dimensions=[("url", DataType.STRING), ("host", DataType.STRING)],
        metrics=[("v", DataType.INT)],
    )
    base = tmp_path_factory.mktemp("fst")
    with_idx = QueryEngine(device_executor=None)
    without = QueryEngine(device_executor=None)
    build_segment(schema, cols, str(base / "i"), TableConfig(
        table_name="logs",
        indexing=IndexingConfig(fst_index_columns=["url", "host"])), "s0")
    build_segment(schema, cols, str(base / "p"), TableConfig(
        table_name="logs"), "s0")
    with_idx.add_segment("logs", ImmutableSegment(str(base / "i")))
    without.add_segment("logs", ImmutableSegment(str(base / "p")))
    return with_idx, without


FST_QUERIES = [
    "SELECT COUNT(*) FROM logs WHERE REGEXP_LIKE(url, 'resource_0042')",
    "SELECT COUNT(*), SUM(v) FROM logs WHERE REGEXP_LIKE(url, '^/api/v2/.*edit$')",
    "SELECT COUNT(*) FROM logs WHERE REGEXP_LIKE(host, 'h3\\.dc[0-9]\\.example')",
    "SELECT COUNT(*) FROM logs WHERE url LIKE '%resource_01%'",
    "SELECT host, COUNT(*) FROM logs WHERE url LIKE '/api/v1/%edit' "
    "GROUP BY host ORDER BY host LIMIT 10",
    "SELECT COUNT(*) FROM logs WHERE REGEXP_LIKE(url, 'nosuchthinganywhere')",
    # alternation: no narrowing possible, must still be correct via scan
    "SELECT COUNT(*) FROM logs WHERE REGEXP_LIKE(url, 'edit$|zzz')",
]


class TestFstQueries:
    @pytest.mark.parametrize("sql", FST_QUERIES)
    def test_indexed_matches_scan(self, engines, sql):
        with_idx, without = engines
        a = with_idx.execute(sql)
        b = without.execute(sql)
        assert not a.get("exceptions"), a
        assert a["resultTable"]["rows"] == b["resultTable"]["rows"]

    def test_index_actually_consulted(self, engines, monkeypatch):
        """The narrow-then-verify path must run for an indexed column —
        count regex evaluations via the candidates hook."""
        with_idx, _ = engines
        from pinot_tpu.storage import fstindex

        calls = []
        real = fstindex.TrigramIndex.candidates

        def spy(self, pattern, n):
            out = real(self, pattern, n)
            calls.append(0 if out is None else len(out))
            return out

        monkeypatch.setattr(fstindex.TrigramIndex, "candidates", spy)
        r = with_idx.execute(
            "SELECT COUNT(*) FROM logs WHERE REGEXP_LIKE(url, 'resource_0042')")
        assert not r.get("exceptions"), r
        assert calls and calls[0] < 50  # narrowed from ~9000 dict entries
