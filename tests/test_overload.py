"""Overload survival: tenant admission, shedding, weighted-fair slots,
autoscaling (ISSUE 14).

The differential that matters: a tenant-A spike with shedding ON vs OFF
must leave tenant B's rows bit-exact and error-free; degraded responses
are typed (``servedStale``/``sheddingReason``), never silent; and the
scheduler's weighted-fair slot accounting keeps one tenant from holding
every server slot.
"""

import threading
import time

import numpy as np
import pytest

from pinot_tpu.broker.admission import TenantAdmissionController
from pinot_tpu.broker.broker import Broker, LoadTracker
from pinot_tpu.cluster.registry import ClusterRegistry, InstanceInfo, Role
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.engine.scheduler import TokenBucketScheduler
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment


def wait_until(cond, timeout=15.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.05)
    return False


def _cluster(tmp_path, n_rows=4_000, admission=None, result_cache=False,
             scheduler_name=None, max_concurrent=8):
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "ds"))
    server = ServerInstance("s0", registry, str(tmp_path / "srv"),
                            device_executor=None,
                            scheduler_name=scheduler_name,
                            max_concurrent_queries=max_concurrent)
    server.start()
    broker = Broker(registry, timeout_s=10.0, result_cache=result_cache,
                    admission=admission)
    schema = Schema.build(name="t", dimensions=[("k", DataType.STRING)],
                          metrics=[("v", DataType.LONG)])
    cfg = TableConfig(table_name="t")
    controller.add_table(cfg, schema)
    rng = np.random.default_rng(14)
    build_segment(schema, {
        "k": np.array(["a", "b", "c", "d"])[rng.integers(0, 4, n_rows)],
        "v": rng.integers(1, 100, n_rows).astype(np.int64),
    }, str(tmp_path / "up"), cfg, "t_0")
    controller.upload_segment("t", str(tmp_path / "up"))
    assert wait_until(
        lambda: len(registry.external_view("t_OFFLINE")) == 1)
    return registry, controller, server, broker


class TestAdmission429:
    def test_429_retry_after_from_tenant_bucket(self, tmp_path):
        """Admission rejections compute Retry-After from the TENANT's
        actual bucket refill time (capped at 5 s) and carry the tenant +
        priority class in the response — never the table-quota's fixed
        0.5 s hint (ISSUE 14 satellite fix)."""
        adm = TenantAdmissionController(rate_qps=0.5, burst=2.0)
        _reg, _ctl, server, broker = _cluster(tmp_path, admission=adm)
        try:
            sql = "SET workloadName='heavy'; SELECT COUNT(*) FROM t"
            rejected = None
            for _ in range(5):
                r = broker.execute(sql)
                if r.get("exceptions"):
                    rejected = r
                    break
            assert rejected is not None, "bucket never went dry"
            exc = rejected["exceptions"][0]
            assert exc["errorCode"] == 429
            assert rejected["sheddingReason"] == "tenant_bucket_dry"
            assert rejected["tenant"] == "heavy"
            assert rejected["priorityClass"] in ("interactive", "dashboard",
                                                 "adhoc")
            # refill at 0.5 tokens/s: ~2 s to one token — NOT the quota
            # path's 0.5, and capped at 5
            assert 0.5 < rejected["retryAfterSeconds"] <= 5.0
            # the query log captured the shed (always-log abnormal)
            entry = broker.querylog.recent(1)[0]
            assert entry["counters"]["sheddingReason"] == "tenant_bucket_dry"
            assert entry["counters"]["tenant"] == "heavy"
        finally:
            broker.close()
            server.stop()

    def test_retry_after_capped_at_5s(self):
        adm = TenantAdmissionController(rate_qps=0.01, burst=1.0)
        assert adm.try_admit("slow", "adhoc").admitted
        d = adm.try_admit("slow", "adhoc")
        assert not d.admitted
        assert d.retry_after_s == pytest.approx(5.0)

    def test_admission_off_by_default(self, tmp_path):
        """No admission controller configured: semantics are exactly the
        pre-ISSUE-14 broker — no tenant fields, no shedding."""
        _reg, _ctl, server, broker = _cluster(tmp_path)
        try:
            assert broker.admission is None
            r = broker.execute(
                "SET workloadName='x'; SELECT COUNT(*) FROM t")
            assert not r.get("exceptions")
            assert "tenant" not in r
        finally:
            broker.close()
            server.stop()


class TestTenantIsolation:
    def test_spike_shed_on_vs_off_tenant_b_parity(self, tmp_path):
        """THE differential: tenant-A spike with shedding on vs off —
        tenant B's rows stay bit-exact, B sees zero errors, and with
        shedding ON the spike is actually shed (typed 429s for A)."""
        b_sql = ("SET workloadName='tenantB'; "
                 "SELECT k, COUNT(*), SUM(v) FROM t GROUP BY k ORDER BY k")
        a_sqls = [f"SET workloadName='tenantA'; "
                  f"SELECT COUNT(*) FROM t WHERE v > {i}" for i in range(40)]

        def run_spike(admission):
            registry = None
            _reg, _ctl, server, broker = _cluster(
                tmp_path / ("on" if admission else "off"),
                admission=admission, scheduler_name="tokenbucket",
                max_concurrent=4)
            try:
                b_rows, b_errors, a_shed = [], [0], [0]
                stop = threading.Event()

                def spike():
                    i = 0
                    while not stop.is_set():
                        r = broker.execute(a_sqls[i % len(a_sqls)])
                        if r.get("sheddingReason"):
                            a_shed[0] += 1
                        i += 1

                threads = [threading.Thread(target=spike, daemon=True)
                           for _ in range(4)]
                for t in threads:
                    t.start()
                for _ in range(10):
                    r = broker.execute(b_sql)
                    if r.get("exceptions"):
                        b_errors[0] += 1
                    else:
                        b_rows.append(r["resultTable"]["rows"])
                    time.sleep(0.02)
                stop.set()
                for t in threads:
                    t.join(3)
                return b_rows, b_errors[0], a_shed[0]
            finally:
                broker.close()
                server.stop()

        rows_off, err_off, _ = run_spike(None)
        # tenant buckets sized like a real deployment: the spiking ad-hoc
        # tenant gets a tight budget, the dashboard tenant's panel rate
        # fits comfortably inside its own
        adm = TenantAdmissionController(
            rate_qps=3.0, burst=4.0,
            tenant_overrides={"tenantB": {"rate": 200.0, "burst": 50.0}})
        rows_on, err_on, shed_on = run_spike(adm)
        assert err_on == 0, "tenant B saw hard errors with shedding on"
        assert rows_on, "tenant B starved entirely under the spike"
        # bit-exact parity: every B answer identical across both runs
        assert rows_off, "shedding-off control run produced no B rows"
        ref = rows_off[0]
        assert all(r == ref for r in rows_off)
        assert all(r == ref for r in rows_on), \
            "tenant B rows drifted between shed-on and shed-off"
        assert shed_on > 0, "the spike was never shed with admission on"

    def test_weighted_fair_slots_interactive_over_adhoc(self):
        """Weighted-fair slot accounting: with an adhoc tenant holding
        slots, a later-arriving interactive (weight 4) waiter is picked
        before the adhoc tenant's next query."""
        # hard limit lifted to the slot count so the WEIGHTED pick (not
        # the cap) is what this test exercises; two separate release
        # events let exactly ONE slot free while adhoc still holds the
        # other — the weighted-share comparison only differs from FIFO
        # while a group actually occupies slots
        sched = TokenBucketScheduler(max_concurrent=2, max_queued=16,
                                     per_group_hard_limit=2)
        rel1, rel2 = threading.Event(), threading.Event()
        holders = [threading.Thread(
            target=lambda e=e: sched.run(lambda: e.wait(5), group="adhoc",
                                         weight=1.0))
            for e in (rel1, rel2)]
        for t in holders:
            t.start()
        assert wait_until(lambda: sched.pressure() == 2, 2)
        order = []
        wa = threading.Thread(target=lambda: sched.run(
            lambda: order.append("adhoc"), group="adhoc", weight=1.0))
        wa.start()
        time.sleep(0.05)  # adhoc waiter arrives FIRST
        wi = threading.Thread(target=lambda: sched.run(
            lambda: order.append("interactive"), group="vip", weight=4.0))
        wi.start()
        assert wait_until(lambda: sched.pressure() == 4, 2)
        rel1.set()  # one slot frees; adhoc STILL holds the other
        wi.join(5)
        rel2.set()
        for t in holders + [wa]:
            t.join(5)
        # with adhoc owning a running slot at pick time, vip's share 0/4
        # beats adhoc's 1/1 — the freed slot went interactive despite
        # adhoc's earlier arrival (vip finishing instantly may then free
        # the slot for adhoc before this thread observes the order, so
        # only the ORDER is asserted, not exclusivity)
        assert order == ["interactive", "adhoc"], order

    def test_one_tenant_cannot_hold_every_slot(self):
        """The per-group hard cap composes with weights: 8 concurrent
        adhoc queries on a 4-slot scheduler never occupy all 4."""
        sched = TokenBucketScheduler(max_concurrent=4, max_queued=32)
        peak = [0]
        lock = threading.Lock()

        def work():
            with lock:
                peak[0] = max(peak[0],
                              sched._running_by_group.get("hog", 0))
            time.sleep(0.02)

        threads = [threading.Thread(
            target=lambda: sched.run(work, group="hog", weight=1.0))
            for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5)
        assert peak[0] <= sched.per_group_hard_limit < 4


class TestBoundedStaleness:
    def _drain(self, broker, tenant="tenantA", n=6):
        for i in range(n):
            broker.execute(f"SET workloadName='{tenant}'; "
                           f"SELECT COUNT(*) FROM t WHERE v > {i}")

    def test_served_stale_only_within_max_staleness(self, tmp_path):
        """A shed query degrades to a result-cache entry ONLY within its
        maxStalenessMs bound — flagged servedStale with the entry age —
        and 429s when the bound excludes the entry."""
        adm = TenantAdmissionController(rate_qps=0.2, burst=3.0)
        registry, controller, server, broker = _cluster(
            tmp_path, admission=adm, result_cache=True)
        try:
            sql = ("SELECT k, SUM(v) FROM t GROUP BY k ORDER BY k")
            r = broker.execute(f"SET workloadName='tenantA'; {sql}")
            assert not r.get("exceptions"), r
            rows = r["resultTable"]["rows"]
            # make the entry freshness-STALE: a second segment bumps the
            # routing generation (a real cluster change)
            schema = registry.table_schema("t_OFFLINE")
            build_segment(schema, {"k": np.array(["e"]),
                                   "v": np.array([7], dtype=np.int64)},
                          str(tmp_path / "up2"),
                          TableConfig(table_name="t"), "t_1")
            controller.upload_segment("t", str(tmp_path / "up2"))
            assert wait_until(
                lambda: len(registry.external_view("t_OFFLINE")) == 2)
            time.sleep(0.1)  # entry age comfortably above 20 ms
            self._drain(broker)
            degraded = broker.execute(
                f"SET workloadName='tenantA'; "
                f"SET maxStalenessMs=60000; {sql}")
            assert degraded.get("servedStale") is True, degraded
            assert degraded["sheddingReason"] == "tenant_bucket_dry"
            assert 0 < degraded["staleAgeMs"] <= 60000
            # the STALE rows (pre-upload) serve — bounded staleness is
            # the contract, and the flag is what makes it honest
            assert degraded["resultTable"]["rows"] == rows
            # a 20 ms bound excludes the (older) entry: typed 429
            rejected = broker.execute(
                f"SET workloadName='tenantA'; "
                f"SET maxStalenessMs=20; {sql}")
            assert rejected["exceptions"][0]["errorCode"] == 429, rejected
            assert rejected.get("servedStale") is None
        finally:
            broker.close()
            server.stop()

    def test_fresh_cache_hit_queue_jumps_dry_bucket(self, tmp_path):
        """A FRESH result-cache hit bypasses admission entirely: repeat
        dashboard panels serve sub-RTT even when their tenant's bucket is
        dry (queue jumping)."""
        adm = TenantAdmissionController(rate_qps=0.2, burst=3.0)
        _reg, _ctl, server, broker = _cluster(
            tmp_path, admission=adm, result_cache=True)
        try:
            sql = "SELECT k, COUNT(*) FROM t GROUP BY k ORDER BY k"
            r = broker.execute(f"SET workloadName='tenantA'; {sql}")
            assert not r.get("exceptions")
            self._drain(broker)
            # bucket is dry — but the repeat is a fresh hit: served, not shed
            hit = broker.execute(f"SET workloadName='tenantA'; {sql}")
            assert hit.get("resultCacheHit") is True, hit
            assert not hit.get("exceptions")
            assert hit.get("sheddingReason") is None
        finally:
            broker.close()
            server.stop()

    def test_bucket_rate_is_tenant_configured_not_first_query(self):
        """Review fix: a per-query SET priorityClass must not set (or
        freeze) the tenant's bucket refill — rate derives from the
        tenant's CONFIGURED class, so a client can't self-upgrade its
        budget and the first query's class doesn't stick forever."""
        adm = TenantAdmissionController(rate_qps=10.0, burst=20.0,
                                        default_priority="dashboard")
        # first contact claims 'interactive' — the bucket still refills
        # at the default-class rate
        adm.try_admit("sneaky", "interactive")
        assert adm._bucket("sneaky").rate == pytest.approx(10.0)
        # a configured-interactive tenant DOES get the scaled rate
        adm2 = TenantAdmissionController(
            rate_qps=10.0, burst=20.0, default_priority="dashboard",
            tenant_overrides={"vip": {"priority": "interactive"}})
        adm2.try_admit("vip", "adhoc")  # query class is irrelevant here
        assert adm2._bucket("vip").rate == pytest.approx(20.0)

    def test_stale_retention_counts_from_staleness_not_put(self):
        """Review fix: an entry fresh for longer than stale_retention_s
        before being invalidated still earns its FULL linger window for
        the shed path (retention counts from first-observed-stale, not
        from put)."""
        from pinot_tpu.broker.result_cache import BrokerResultCache

        cache = BrokerResultCache(stale_retention_s=30.0)
        key = ("t", "tpl", "digest")
        cache.put(key, {"rows": 1}, {"s0": 1}, routing_gen=1)
        # age the entry far past the retention window while FRESH
        with cache._lock:
            cache._entries[key]["ts"] -= 120.0
        # first stale observation (epoch drift): entry must survive...
        assert cache.get(key, {"s0": 2}, 1) is None
        stale, age_s = cache.get_stale(key, max_age_s=300.0)
        assert stale == {"rows": 1}
        assert age_s >= 120.0
        # ...until the linger window elapses from the OBSERVATION
        with cache._lock:
            cache._entries[key]["stale_since"] -= 31.0
        assert cache.get(key, {"s0": 2}, 1) is None  # drops now
        stale, _age = cache.get_stale(key, max_age_s=300.0)
        assert stale is None

    def test_subrtt_digest_admits_at_reduced_cost(self):
        adm = TenantAdmissionController(rate_qps=0.001, burst=1.0)
        key = ("t", "template", "digest")
        adm.note_sub_rtt(key)
        assert adm.is_sub_rtt(key)
        # 1.0 burst funds ten 0.1-cost sub-RTT admissions, one full-cost
        for _ in range(9):
            assert adm.try_admit("a", "dashboard", sub_rtt=True).admitted
        assert not adm.try_admit("a", "dashboard", sub_rtt=False).admitted


class TestLoadShedLadder:
    def test_priority_ladder(self):
        adm = TenantAdmissionController(shed_load_threshold=4.0)
        # at the threshold: adhoc sheds, dashboard + interactive pass
        assert not adm.try_admit("x", "adhoc", load_score=4.0).admitted
        assert adm.try_admit("x", "dashboard", load_score=4.0).admitted
        # at 1.5x: dashboard sheds too
        d = adm.try_admit("x", "dashboard", load_score=6.0)
        assert not d.admitted and d.reason == "load_shed"
        assert adm.try_admit("x", "interactive", load_score=6.0).admitted
        # at 2x: everyone sheds — except known-sub-RTT repeats
        assert not adm.try_admit("x", "interactive", load_score=8.0).admitted
        assert adm.try_admit("x", "adhoc", load_score=8.0,
                             sub_rtt=True).admitted


class TestLoadTrackerStaleness:
    def test_heartbeat_stale_observation_expires(self):
        """ISSUE 14 satellite fix: a crashed server's frozen pressure
        sample must expire out of scoring (score -> None), not decay
        toward 0 and read as the idlest pick."""
        lt = LoadTracker()
        now = time.monotonic()
        lt.observe("dead", 8.0, ts=now - 10.0)
        assert lt.score("dead") is not None  # within STALE_S: still scored
        lt.expire_if_stale("dead", LoadTracker.HB_STALE_S)
        assert lt.score("dead") is None
        # a FRESH observation survives the same sweep
        lt.observe("alive", 2.0)
        lt.expire_if_stale("alive", LoadTracker.HB_STALE_S)
        assert lt.score("alive") is not None

    def test_router_refresh_expires_heartbeat_stale_instance(self, tmp_path):
        """End to end through RoutingManager._refresh_heartbeat_loads: an
        instance whose registry heartbeat is older than 3 intervals drops
        out of the load view."""
        registry = ClusterRegistry()
        broker = Broker(registry)
        try:
            registry.register_instance(InstanceInfo("dead", Role.SERVER))
            # plant a load observation as a piggybacked response would,
            # then age BOTH the heartbeat and the observation
            old = time.monotonic() - 2 * LoadTracker.HB_STALE_S
            broker.routing.loads.observe("dead", 9.0, ts=old)
            registry._tx(lambda s: setattr(
                s["instances"]["dead"], "last_heartbeat_ms",
                int((time.time() - 20) * 1000)))
            broker.routing._last_hb_refresh = 0.0
            broker.routing._refresh_heartbeat_loads()
            assert broker.routing.loads.score("dead") is None
        finally:
            broker.close()


class TestAutoscaler:
    def test_scale_out_and_drain_cycle(self, tmp_path):
        """Sustained pressure scales 2 -> 4; subsiding load drains back
        to 2; heartbeat-stale instances count as missing capacity."""
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        counter = [2]
        for i in range(2):
            registry.register_instance(InstanceInfo(f"srv_{i}", Role.SERVER))
            registry.heartbeat(f"srv_{i}", pressure=8.0)

        def spawn():
            i = counter[0]
            counter[0] += 1
            registry.register_instance(InstanceInfo(f"srv_{i}", Role.SERVER))
            registry.heartbeat(f"srv_{i}", pressure=0.0)
            return f"srv_{i}"

        drained = []

        def drain(inst):
            drained.append(inst)
            registry.drop_instance(inst)
            return True

        controller.attach_autoscaler(
            spawn, drain, min_servers=2, max_servers=4,
            high_water=4.0, low_water=0.5, sustain_ticks=2,
            cooldown_ticks=0)
        for _ in range(6):
            controller.run_autoscale()
        assert len(registry.instances(Role.SERVER)) == 4
        state = registry.autoscaler_state()
        assert state["scaleOuts"] == 2
        for i in registry.instances(Role.SERVER):
            registry.heartbeat(i.instance_id, pressure=0.0)
        for _ in range(8):
            controller.run_autoscale()
        assert len(registry.instances(Role.SERVER)) == 2
        assert registry.autoscaler_state()["scaleIns"] == 2
        assert len(drained) == 2

    def test_never_exceeds_bounds_and_sustain_required(self, tmp_path):
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        registry.register_instance(InstanceInfo("srv_0", Role.SERVER))
        registry.heartbeat("srv_0", pressure=100.0)
        spawned = []

        def spawn():
            sid = f"x{len(spawned)}"
            spawned.append(sid)
            registry.register_instance(InstanceInfo(sid, Role.SERVER))
            registry.heartbeat(sid, pressure=100.0)
            return sid

        controller.attach_autoscaler(
            spawn, lambda i: True, min_servers=1, max_servers=2,
            high_water=4.0, low_water=0.5, sustain_ticks=3,
            cooldown_ticks=0)
        # two ticks: below the sustain bar — no action yet
        controller.run_autoscale()
        controller.run_autoscale()
        assert spawned == []
        controller.run_autoscale()
        assert spawned == ["x0"]
        # at max: pressure stays high but the fleet is capped
        for _ in range(5):
            controller.run_autoscale()
        assert len(spawned) == 1

    def test_stale_heartbeats_do_not_count_as_capacity(self, tmp_path):
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        registry.register_instance(InstanceInfo("live", Role.SERVER))
        registry.heartbeat("live", pressure=8.0)
        registry.register_instance(InstanceInfo("dead", Role.SERVER))
        registry._tx(lambda s: setattr(
            s["instances"]["dead"], "last_heartbeat_ms",
            int((time.time() - 60) * 1000)))
        scaler = controller.attach_autoscaler(
            lambda: None, lambda i: True, min_servers=1, max_servers=4,
            high_water=4.0, low_water=0.5)
        live, mean = scaler._live_pressure()
        assert live == ["live"]
        assert mean == pytest.approx(8.0)
