"""Narrow-width device residency: width planning + differential parity.

The contract under test (ISSUE 5): device column planes store at their
cardinality-chosen width — uint8/uint16/int32 dict-id planes, frame-of-
reference (min-offset) downcast for raw/decoded int planes, an opt-in
sub-byte tier (PINOT_TPU_SUBBYTE=1) unpacked in-kernel — with zone maps
narrowing alongside, and every query over narrow planes answers EXACTLY
like the forced-wide legacy layout (PINOT_TPU_FORCE_WIDE=1) and
value-equal to the host executor, across EQ/IN/RANGE/NOT predicates,
scalar + group-by aggregations, sealed + consuming segments, solo +
8-dev mesh, cardinality boundaries (255/256, 65535/65536), and
eviction churn under a shrunken byte budget.
"""

import os

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.engine.params import BatchContext, ColPlan, _int_for_plan
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment

N_SEG = 2
ROWS = 8192


def _build_table(base, seed=11):
    rng = np.random.default_rng(seed)
    schema = Schema.build(
        name="nw",
        dimensions=[("tag", DataType.STRING), ("mid", DataType.INT),
                    ("ts", DataType.LONG)],
        metrics=[("m", DataType.INT), ("f", DataType.DOUBLE)],
    )
    cfg = TableConfig(
        table_name="nw",
        indexing=IndexingConfig(no_dictionary_columns=["ts", "m"]),
    )
    segs, all_cols = [], []
    for i in range(N_SEG):
        cols = {
            # dict str, card 3 -> uint8 (2-bit under the sub-byte tier)
            "tag": np.array(["a", "b", "c"])[rng.integers(0, 3, ROWS)],
            # dict int, card ~300 -> uint16
            "mid": rng.integers(0, 300, ROWS).astype(np.int32),
            # raw int64, huge base but tiny range -> FOR uint16 + offset
            "ts": (10_000_000_000 + i * ROWS
                   + np.arange(ROWS)).astype(np.int64),
            # raw int32, values 0..9999 -> plain uint16 (no offset)
            "m": rng.integers(0, 10_000, ROWS).astype(np.int32),
            # raw double -> f32 (legacy device float space)
            "f": np.round(rng.uniform(0, 100, ROWS), 3),
        }
        all_cols.append(cols)
        build_segment(schema, cols, str(base / f"s{i}"), cfg, f"s{i}")
        segs.append(ImmutableSegment(str(base / f"s{i}")))
    return segs, all_cols


@pytest.fixture(scope="module")
def tables(tmp_path_factory):
    return _build_table(tmp_path_factory.mktemp("narrow"))


def _engine(segs, device="auto", table="nw"):
    eng = QueryEngine() if device == "auto" \
        else QueryEngine(device_executor=device)
    for s in segs:
        eng.add_segment(table, s)
    return eng


@pytest.fixture(scope="module")
def engines(tables):
    segs, all_cols = tables
    narrow = _engine(segs)
    os.environ["PINOT_TPU_FORCE_WIDE"] = "1"
    try:
        wide = _engine(segs)
        # materialize the wide engine's BatchContext while the env flag is
        # up (plans are sampled at BatchContext creation)
        wide.execute("SELECT COUNT(*) FROM nw")
    finally:
        del os.environ["PINOT_TPU_FORCE_WIDE"]
    host = _engine(segs, device=None)
    return narrow, wide, host, all_cols


# EQ / IN / RANGE / NOT over every width tier; scalar + group-by shapes;
# FOR columns filtered in raw value space; empty + unselective.
PARITY_QUERIES = [
    "SELECT COUNT(*), SUM(m), MIN(m), MAX(m) FROM nw WHERE tag = 'b'",
    "SELECT COUNT(*), AVG(m) FROM nw WHERE mid IN (5, 250, 299)",
    "SELECT COUNT(*), SUM(m) FROM nw "
    "WHERE ts BETWEEN 10000000100 AND 10000004000",
    "SELECT COUNT(*), MIN(ts), MAX(ts) FROM nw WHERE m < 100",
    "SELECT COUNT(*) FROM nw WHERE NOT tag = 'a' AND m >= 5000",
    "SELECT tag, COUNT(*), SUM(m), MIN(ts), MAX(ts) FROM nw "
    "GROUP BY tag ORDER BY tag",
    "SELECT mid, COUNT(*), SUM(f) FROM nw WHERE tag = 'c' "
    "GROUP BY mid ORDER BY mid LIMIT 10",
    "SELECT COUNT(*), DISTINCTCOUNT(tag), DISTINCTCOUNT(mid) FROM nw "
    "WHERE m > 2000",
    "SELECT COUNT(*), MINMAXRANGE(m) FROM nw WHERE mid = 7 OR mid = 123",
    # empty (absent dict value) and empty-but-unprunable
    "SELECT COUNT(*), MIN(m), MAX(m) FROM nw WHERE tag = 'zzz'",
    "SELECT COUNT(*), MIN(ts), MAX(ts) FROM nw WHERE m = 1 AND m = 2",
    # unselective full scan
    "SELECT COUNT(*), SUM(m) FROM nw WHERE ts >= 0",
]


def _close(a, b):
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, str) or isinstance(b, str):
        return a == b
    return np.isclose(float(a), float(b), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("sql", PARITY_QUERIES)
def test_narrow_equals_wide_equals_host(engines, sql):
    narrow, wide, host, _ = engines
    rn, rw, rh = narrow.execute(sql), wide.execute(sql), host.execute(sql)
    assert not rn.get("exceptions"), rn
    assert not rw.get("exceptions"), rw
    # narrow vs forced-wide: EXACT — the decode (in-register widen +
    # offset add) reconstructs the same values the wide plane stored
    assert rn["resultTable"] == rw["resultTable"], sql
    assert rn["numDocsScanned"] == rw["numDocsScanned"], sql
    # vs host: value-equal (device floats are f32-narrowed, as before)
    rows_n, rows_h = rn["resultTable"]["rows"], rh["resultTable"]["rows"]
    assert len(rows_n) == len(rows_h), sql
    for a, b in zip(rows_n, rows_h):
        assert all(_close(x, y) for x, y in zip(a, b)), (sql, a, b)


class TestWidthPlans:
    def test_tier_assignment(self, tables):
        segs, _ = tables
        ctx = BatchContext(segs)
        assert ctx.width_plan("tag") == ColPlan("|u1")
        assert ctx.width_plan("mid").dtype == np.dtype(np.uint16).str
        ts = ctx.width_plan("ts")
        assert ts.dtype == np.dtype(np.uint16).str
        assert ts.offset == 10_000_000_000
        assert np.dtype(ts.wide) == np.int64
        m = ctx.width_plan("m")
        assert m.dtype == np.dtype(np.uint16).str and m.offset is None
        assert ctx.width_plan("f").dtype == np.dtype(np.float32).str
        # decoded plane of the int dict column narrows too
        assert np.dtype(ctx.width_plan("dv::mid").dtype).itemsize <= 2

    def test_force_wide_restores_legacy(self, tables, monkeypatch):
        segs, _ = tables
        monkeypatch.setenv("PINOT_TPU_FORCE_WIDE", "1")
        ctx = BatchContext(segs)
        assert np.dtype(ctx.width_plan("tag").dtype) == np.int32
        assert np.dtype(ctx.width_plan("ts").dtype) == np.int64
        assert np.dtype(ctx.width_plan("m").dtype) == np.int32

    def test_int_plan_dtype_extremes(self):
        """FOR planning near int64 extremes must not overflow (python-int
        bounds arithmetic) and must bail to the base dtype when the range
        itself exceeds uint32."""
        i64 = np.dtype(np.int64)
        lo = -(1 << 62)
        p = _int_for_plan(lo, lo + 65_000, i64)
        assert np.dtype(p.dtype) == np.uint16 and p.offset == lo
        p = _int_for_plan(lo, lo + (1 << 33), i64)
        assert np.dtype(p.dtype) == np.int64 and p.offset is None
        p = _int_for_plan(-(1 << 63), (1 << 63) - 1, i64)
        assert np.dtype(p.dtype) == np.int64 and p.offset is None
        # int64 values that fit int32 natively: plain downcast, no offset
        p = _int_for_plan(-(1 << 30), 1 << 30, i64)
        assert np.dtype(p.dtype) == np.int32 and p.offset is None

    def test_zone_maps_narrow_with_column(self, tables):
        segs, _ = tables
        ctx = BatchContext(segs)
        ctx.column("ts")
        zlo, zhi = ctx.zone_map("ts")
        assert zlo.dtype == np.uint16 and zhi.dtype == np.uint16


class TestCardinalityBoundaries:
    @pytest.mark.parametrize("card,want", [
        (255, np.uint8), (256, np.uint16),
        (65535, np.uint16), (65536, np.int32),
    ])
    def test_dict_tier_boundary(self, tmp_path, card, want):
        schema = Schema.build(
            name="cb", dimensions=[("g", DataType.INT)],
            metrics=[("m", DataType.INT)])
        cfg = TableConfig(table_name="cb")
        n = max(card, 4096)
        cols = {"g": (np.arange(n, dtype=np.int64) % card).astype(np.int32),
                "m": np.ones(n, dtype=np.int32)}
        d = str(tmp_path / f"c{card}")
        build_segment(schema, cols, d, cfg, f"c{card}")
        seg = ImmutableSegment(d)
        ctx = BatchContext([seg])
        plan = ctx.width_plan("g")
        assert np.dtype(plan.dtype) == want, plan
        eng = _engine([seg], table="cb")
        host = _engine([seg], device=None, table="cb")
        for sql in (f"SELECT COUNT(*) FROM cb WHERE g = {card - 1}",
                    f"SELECT COUNT(*) FROM cb WHERE g IN (0, {card - 1})",
                    "SELECT COUNT(*), DISTINCTCOUNT(g) FROM cb"):
            rd, rh = eng.execute(sql), host.execute(sql)
            assert not rd.get("exceptions"), (sql, rd)
            assert rd["resultTable"]["rows"] == rh["resultTable"]["rows"], sql


class TestSubByteTier:
    def test_unpack_matches_numpy(self):
        import jax.numpy as jnp

        from pinot_tpu.ops.masks import unpack_subbyte

        rng = np.random.default_rng(5)
        for bits in (2, 4):
            ids = rng.integers(0, 1 << bits, (3, 128)).astype(np.uint8)
            packed = BatchContext._pack_subbyte_np(ids, bits)
            assert packed.shape == (3, 128 * bits // 8)
            got = np.asarray(unpack_subbyte(jnp.asarray(packed), bits))
            np.testing.assert_array_equal(got, ids)

    def test_subbyte_opt_in_parity(self, tables, monkeypatch):
        segs, _ = tables
        monkeypatch.setenv("PINOT_TPU_SUBBYTE", "1")
        ctx = BatchContext(segs)
        plan = ctx.width_plan("tag")  # card 3 -> 2-bit
        assert plan.bits == 2
        col = ctx.column("tag")
        assert col.shape == (N_SEG, ctx.pad_to // 4)
        sub = _engine(segs)
        host = _engine(segs, device=None)
        for sql in (
            "SELECT COUNT(*), SUM(m) FROM nw WHERE tag = 'b'",
            "SELECT tag, COUNT(*), MIN(m) FROM nw GROUP BY tag ORDER BY tag",
            "SELECT COUNT(*) FROM nw WHERE tag IN ('a', 'c') "
            "AND ts BETWEEN 10000000100 AND 10000002000",
            "SELECT COUNT(*), DISTINCTCOUNT(tag) FROM nw WHERE m > 100",
        ):
            rd, rh = sub.execute(sql), host.execute(sql)
            assert not rd.get("exceptions"), (sql, rd)
            assert rd["resultTable"]["rows"] == rh["resultTable"]["rows"], sql
        # default (opt-out) stays byte-aligned
        assert BatchContext._pack_subbyte_np is not None
        monkeypatch.delenv("PINOT_TPU_SUBBYTE")
        assert BatchContext(segs).width_plan("tag").bits == 0

    def test_subbyte_mesh_parity(self, tables, monkeypatch):
        """Sub-byte planes shard like any column ((S, L//f) packed byte
        axis) and unpack inside each shard's kernel."""
        from pinot_tpu.engine.device import DeviceExecutor
        from pinot_tpu.parallel.mesh import make_mesh

        segs, _ = tables
        monkeypatch.setenv("PINOT_TPU_SUBBYTE", "1")
        mesh_eng = _engine(segs, DeviceExecutor(mesh=make_mesh(8)))
        host = _engine(segs, None)
        for sql in (
            "SELECT COUNT(*), SUM(m) FROM nw WHERE tag = 'b' "
            "AND ts BETWEEN 10000000100 AND 10000009000",
            "SELECT tag, COUNT(*), MIN(m), MAX(ts) FROM nw "
            "GROUP BY tag ORDER BY tag",
        ):
            rm, rh = mesh_eng.execute(sql), host.execute(sql)
            assert not rm.get("exceptions"), (sql, rm)
            assert rm["resultTable"]["rows"] == rh["resultTable"]["rows"], sql


class TestMesh:
    @pytest.mark.parametrize("sql", PARITY_QUERIES[:6])
    def test_mesh_parity(self, tables, sql):
        from pinot_tpu.engine.device import DeviceExecutor
        from pinot_tpu.parallel.mesh import make_mesh

        segs, _ = tables
        mesh_eng = _engine(segs, DeviceExecutor(mesh=make_mesh(8)))
        host_eng = _engine(segs, None)
        rm, rh = mesh_eng.execute(sql), host_eng.execute(sql)
        assert not rm.get("exceptions"), rm
        rows_m, rows_h = rm["resultTable"]["rows"], rh["resultTable"]["rows"]
        assert len(rows_m) == len(rows_h), sql
        for a, b in zip(rows_m, rows_h):
            assert all(_close(x, y) for x, y in zip(a, b)), (sql, a, b)


class TestConsumingSegments:
    def test_chunklet_planes_narrow_like_sealed(self, tmp_path):
        """Consuming segments' promoted chunklets ride the SAME BatchContext
        width planning as sealed segments — parity vs an all-host engine
        while the tail stays unfrozen."""
        from pinot_tpu.common.table_config import ChunkletConfig
        from pinot_tpu.realtime.chunklet import split_for_query
        from pinot_tpu.storage.mutable import MutableSegment

        schema = Schema.build(
            name="rt", dimensions=[("tag", DataType.STRING)],
            metrics=[("m", DataType.INT)])
        cfg = TableConfig(
            table_name="rt",
            chunklets=ChunkletConfig(enabled=True, rows_per_chunklet=4096,
                                     device_min_rows=0))
        seg = MutableSegment(schema, "rt__0", cfg)
        rng = np.random.default_rng(17)
        n = 11_000  # 2 promotable chunklets + a host tail
        tags = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
        ms = rng.integers(0, 50, n)
        seg.index_batch([{"tag": str(t), "m": int(v)}
                         for t, v in zip(tags, ms)])
        seg.chunklet_index.promote()
        dev = _engine([seg], table="rt")
        host = _engine([seg], None, table="rt")
        for sql in ("SELECT COUNT(*), SUM(m) FROM rt WHERE tag = 'b'",
                    "SELECT tag, COUNT(*), MAX(m) FROM rt "
                    "GROUP BY tag ORDER BY tag"):
            rd, rh = dev.execute(sql), host.execute(sql)
            assert not rd.get("exceptions"), rd
            assert rd["resultTable"]["rows"] == rh["resultTable"]["rows"], sql
        # the chunklet batch planned narrow id planes (card 3 -> uint8)
        split = split_for_query(seg)
        assert split is not None and split[0], "no chunklets promoted"
        ctx = BatchContext(split[0])
        assert np.dtype(ctx.width_plan("tag").dtype) == np.uint8


class TestHbmAccounting:
    def test_resident_bytes_shrink(self, tables):
        """The headline claim: a dict-heavy batch's resident bytes shrink
        >= 2.5x vs the r05 wide layout for the same columns."""
        segs, _ = tables
        narrow = BatchContext(segs)
        os.environ["PINOT_TPU_FORCE_WIDE"] = "1"
        try:
            wide = BatchContext(segs)
        finally:
            del os.environ["PINOT_TPU_FORCE_WIDE"]
        for c in ("tag", "mid", "ts", "m"):
            narrow.column(c)
            wide.column(c)
        assert wide.device_bytes() >= 2.5 * narrow.device_bytes(), (
            wide.device_bytes(), narrow.device_bytes())
        # saved-bytes accounting matches the actual delta
        assert narrow.narrow_saved_bytes() == \
            wide.device_bytes() - narrow.device_bytes()
        assert wide.narrow_saved_bytes() == 0

    def test_executor_counters(self, tables):
        segs, _ = tables
        eng = _engine(segs)
        eng.execute("SELECT COUNT(*), SUM(m) FROM nw WHERE tag = 'a'")
        eng.execute("SELECT COUNT(*), SUM(m) FROM nw WHERE tag = 'b'")
        snap = eng.device.hbm_stats()
        assert snap["batch_misses"] == 1
        assert snap["batch_hits"] >= 1
        assert snap["cached_batches"] == 1
        assert snap["resident_bytes"] > 0
        assert snap["narrow_saved_bytes"] > 0
        assert snap["batches"][0]["segments"] == N_SEG

    def test_eviction_churn_parity(self, tmp_path):
        """Two tables alternating under a byte budget that holds only one
        batch: every re-admission rebuilds narrow planes and answers must
        stay stable; the eviction counter proves churn happened."""
        segs_a, _ = _build_table(tmp_path / "a", seed=23)
        segs_b, _ = _build_table(tmp_path / "b", seed=29)
        eng = QueryEngine()
        for s in segs_a:
            eng.add_segment("nw", s)
        for s in segs_b:
            eng.add_segment("nw2", s)
        eng.device.MAX_CACHED_BATCHES = 1
        sqls = ("SELECT COUNT(*), SUM(m) FROM nw WHERE tag = 'b'",
                "SELECT COUNT(*), SUM(m) FROM nw2 WHERE tag = 'b'")
        first = [eng.execute(s)["resultTable"] for s in sqls]
        for _ in range(2):
            for sql, want in zip(sqls, first):
                assert eng.execute(sql)["resultTable"] == want
        assert eng.device.hbm_stats()["batch_evictions"] >= 2


class TestWidthAudit:
    def test_audit_passes_and_logs(self, tables, monkeypatch, caplog):
        import logging

        segs, _ = tables
        monkeypatch.setenv("PINOT_TPU_WIDTH_AUDIT", "1")
        eng = _engine(segs)
        with caplog.at_level(logging.INFO, logger="pinot_tpu.device"):
            r = eng.execute(
                "SELECT COUNT(*), SUM(m) FROM nw WHERE tag = 'b'")
        assert not r.get("exceptions"), r
        assert any("width audit" in m for m in caplog.messages)
        assert any("tag: uint8" in m for m in caplog.messages)

    def test_explain_width_table(self, tables, monkeypatch):
        segs, _ = tables
        monkeypatch.setenv("PINOT_TPU_WIDTH_AUDIT", "1")
        eng = _engine(segs)
        r = eng.execute(
            "EXPLAIN PLAN FOR SELECT COUNT(*) FROM nw "
            "WHERE tag = 'b' AND ts > 10000000100")
        ops = [row[0] for row in r["resultTable"]["rows"]]
        assert any("WIDTH(tag: uint8" in o for o in ops), ops
        assert any("WIDTH(ts: uint16 for-offset=10000000000" in o
                   for o in ops), ops

    def test_audit_rejects_upcast(self, tables):
        from pinot_tpu.engine.device import _width_audit

        segs, _ = tables
        ctx = BatchContext(segs)
        cols = {"tag": np.zeros((N_SEG, 64), dtype=np.int32)}
        with pytest.raises(AssertionError, match="upcast"):
            _width_audit(ctx, cols, {"tag": ("|u1", 0, False, "")})
