"""Multi-value columns end-to-end: storage round-trip, match-any predicates
(host + device), MV group-by expansion, *MV aggregation functions, mutable
segments, and DataTable wire round-trip.

Reference analogs: FixedBitMVForwardIndexReader, per-entry ValueMatchers,
aggregateGroupByMV (AggregationFunction.java), SumMV/CountMV/...
AggregationFunction classes.
"""

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.mutable import MutableSegment
from pinot_tpu.storage.segment import ImmutableSegment

N = 5_000


def make_schema():
    return Schema.build(
        name="ev",
        dimensions=[("user", DataType.STRING)],
        multi_value_dimensions=[("tags", DataType.STRING), ("ports", DataType.INT)],
        metrics=[("amount", DataType.INT)],
    )


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(21)
    tags_pool = np.array([f"t{i}" for i in range(12)])
    rows = {
        "user": [f"u{i % 50}" for i in range(N)],
        "tags": [
            list(tags_pool[rng.choice(12, size=rng.integers(0, 4), replace=False)])
            for _ in range(N)
        ],
        "ports": [list(rng.integers(0, 100, rng.integers(1, 5))) for _ in range(N)],
        "amount": rng.integers(0, 1000, N).astype(np.int32),
    }
    return rows


@pytest.fixture(scope="module")
def seg(tmp_path_factory, data):
    out = str(tmp_path_factory.mktemp("mv") / "s0")
    build_segment(make_schema(), data, out, TableConfig(table_name="ev"), "s0")
    return ImmutableSegment(str(out))


def _engine(seg, device=None):
    eng = QueryEngine(device_executor=device)
    eng.add_segment("ev", seg)
    return eng


def _has_tag(data, i, t):
    return t in data["tags"][i]


class TestStorage:
    def test_roundtrip_values(self, seg, data):
        vals = seg.values("tags")
        assert list(vals[0]) == list(data["tags"][0])
        assert list(vals[N - 1]) == list(data["tags"][N - 1])
        meta = seg.column_metadata("tags")
        assert not meta.single_value
        assert meta.max_mv_entries <= 3
        assert meta.total_number_of_entries == sum(len(r) for r in data["tags"])

    def test_flat_values_and_offsets(self, seg, data):
        flat = seg.flat_values("ports")
        off = np.asarray(seg.mv_offsets("ports"))
        assert len(flat) == off[-1]
        i = 137
        assert list(flat[off[i]: off[i + 1]]) == list(data["ports"][i])


class TestHostPredicates:
    def test_match_any_eq(self, seg, data):
        r = _engine(seg).execute("SELECT COUNT(*) FROM ev WHERE tags = 't3'")
        exp = sum(1 for i in range(N) if _has_tag(data, i, "t3"))
        assert r["resultTable"]["rows"][0][0] == exp

    def test_match_any_in(self, seg, data):
        r = _engine(seg).execute("SELECT COUNT(*) FROM ev WHERE tags IN ('t1', 't7')")
        exp = sum(
            1 for i in range(N)
            if _has_tag(data, i, "t1") or _has_tag(data, i, "t7")
        )
        assert r["resultTable"]["rows"][0][0] == exp

    def test_match_any_range_numeric(self, seg, data):
        r = _engine(seg).execute("SELECT COUNT(*) FROM ev WHERE ports BETWEEN 90 AND 99")
        exp = sum(1 for row in data["ports"] if any(90 <= p <= 99 for p in row))
        assert r["resultTable"]["rows"][0][0] == exp

    def test_not_semantics(self, seg, data):
        # SQL NOT: doc-level negation of the match-any predicate
        r = _engine(seg).execute("SELECT COUNT(*) FROM ev WHERE NOT tags = 't3'")
        exp = sum(1 for i in range(N) if not _has_tag(data, i, "t3"))
        assert r["resultTable"]["rows"][0][0] == exp
        # != : per-entry semantics — ANY entry different (reference MV NotEq)
        r = _engine(seg).execute("SELECT COUNT(*) FROM ev WHERE tags != 't3'")
        exp = sum(
            1 for row in data["tags"] if any(t != "t3" for t in row)
        )
        assert r["resultTable"]["rows"][0][0] == exp


class TestDevicePredicates:
    def test_device_matches_host(self, seg, data):
        from pinot_tpu.engine.device import DeviceExecutor

        dev = _engine(seg, DeviceExecutor(mm_mode="interpret"))
        host = _engine(seg)
        for where in ("tags = 't3'", "tags IN ('t1','t7')",
                      "ports BETWEEN 90 AND 99", "tags != 't3'"):
            sql = f"SELECT COUNT(*), SUM(amount) FROM ev WHERE {where}"
            rd = dev.execute(sql)
            rh = host.execute(sql)
            assert not rd.get("exceptions"), rd
            assert rd["resultTable"]["rows"] == rh["resultTable"]["rows"], where


class TestGroupBy:
    def test_mv_groupby_expansion(self, seg, data):
        r = _engine(seg).execute(
            "SELECT tags, COUNT(*), SUM(amount) FROM ev GROUP BY tags ORDER BY tags LIMIT 50"
        )
        exp_count: dict = {}
        exp_sum: dict = {}
        for i, row in enumerate(data["tags"]):
            for t in row:
                exp_count[t] = exp_count.get(t, 0) + 1
                exp_sum[t] = exp_sum.get(t, 0) + int(data["amount"][i])
        got = r["resultTable"]["rows"]
        assert len(got) == len(exp_count)
        for tag, cnt, s in got:
            assert cnt == exp_count[tag], tag
            assert s == exp_sum[tag], tag

    def test_mv_plus_sv_groupby(self, seg, data):
        r = _engine(seg).execute(
            "SELECT user, tags, COUNT(*) FROM ev WHERE user = 'u7' "
            "GROUP BY user, tags ORDER BY tags LIMIT 50"
        )
        exp: dict = {}
        for i in range(N):
            if data["user"][i] == "u7":
                for t in data["tags"][i]:
                    exp[t] = exp.get(t, 0) + 1
        got = r["resultTable"]["rows"]
        assert {(u, t): c for u, t, c in got} == {("u7", t): c for t, c in exp.items()}


class TestMVAggregations:
    def test_countmv_summv(self, seg, data):
        r = _engine(seg).execute("SELECT COUNTMV(ports), SUMMV(ports) FROM ev")
        exp_c = sum(len(p) for p in data["ports"])
        exp_s = sum(sum(p) for p in data["ports"])
        assert r["resultTable"]["rows"][0] == [exp_c, exp_s]

    def test_grouped_mv_aggs(self, seg, data):
        r = _engine(seg).execute(
            "SELECT user, COUNTMV(ports), MINMV(ports), MAXMV(ports), AVGMV(ports), "
            "DISTINCTCOUNTMV(tags) FROM ev WHERE user IN ('u3', 'u4') "
            "GROUP BY user ORDER BY user"
        )
        for row in r["resultTable"]["rows"]:
            u = row[0]
            ports = [p for i, p in enumerate(data["ports"]) if data["user"][i] == u]
            tags = [t for i, ts in enumerate(data["tags"]) if data["user"][i] == u
                    for t in ts]
            flat = [x for p in ports for x in p]
            assert row[1] == len(flat)
            assert row[2] == min(flat)
            assert row[3] == max(flat)
            assert abs(row[4] - sum(flat) / len(flat)) < 1e-9
            assert row[5] == len(set(tags))


class TestSelectionAndWire:
    def test_select_mv_column(self, seg, data):
        r = _engine(seg).execute(
            "SELECT user, tags FROM ev WHERE user = 'u1' LIMIT 5"
        )
        assert not r.get("exceptions"), r
        for row in r["resultTable"]["rows"]:
            assert row[0] == "u1"
            assert isinstance(row[1], list)

    def test_datatable_roundtrip_mv_rows(self, seg):
        from pinot_tpu.engine import datatable
        from pinot_tpu.engine.host import HostExecutor
        from pinot_tpu.sql.compiler import compile_query

        q = compile_query("SELECT tags, amount FROM ev LIMIT 7")
        res = HostExecutor().execute_segment(q, seg)
        back = datatable.decode(datatable.encode(res))
        for a, b in zip(res.rows[0], back.rows[0]):
            assert list(a) == list(b)


class TestMutableMV:
    def test_mutable_mv_index_query_seal(self, tmp_path):
        seg = MutableSegment(make_schema(), "m0")
        rows = [
            {"user": "a", "tags": ["x", "y"], "ports": [1, 2], "amount": 10},
            {"user": "b", "tags": ["y"], "ports": [3], "amount": 20},
            {"user": "a", "tags": [], "ports": [5, 6, 7], "amount": 30},
        ]
        for row in rows:
            seg.index(row)
        eng = QueryEngine()
        eng.table("ev").add_segment(seg)
        r = eng.execute("SELECT COUNT(*) FROM ev WHERE tags = 'y'")
        assert r["resultTable"]["rows"][0][0] == 2
        r = eng.execute("SELECT COUNTMV(ports), SUMMV(ports) FROM ev")
        assert r["resultTable"]["rows"][0] == [6, 24]
        r = eng.execute("SELECT tags, COUNT(*) FROM ev GROUP BY tags ORDER BY tags")
        assert [list(x) for x in r["resultTable"]["rows"]] == [["x", 1], ["y", 2]]

        sealed = seg.seal(str(tmp_path / "sealed"))
        eng2 = QueryEngine()
        eng2.table("ev").add_segment(sealed)
        r = eng2.execute("SELECT COUNT(*) FROM ev WHERE tags = 'y'")
        assert r["resultTable"]["rows"][0][0] == 2
        r = eng2.execute("SELECT COUNTMV(ports), SUMMV(ports) FROM ev")
        assert r["resultTable"]["rows"][0] == [6, 24]
