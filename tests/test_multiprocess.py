"""True multi-PROCESS cluster: server and broker as separate OS processes
coordinating through a FileRegistry, driven end-to-end over HTTP.

Reference analog: the integration suites start all roles in one JVM
(ClusterTest.java); the repo's other cluster tests do the same in-process.
This tier proves the multi-process contract the admin CLI documents —
separate interpreters, shared state only through the registry file and
deep store, queries over the public HTTP endpoint.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig

def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(args, log_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in [os.getcwd()] + env.get("PYTHONPATH", "").split(os.pathsep)
        if p)
    # the CPU test config must not leak a TPU platform requirement
    env.setdefault("JAX_PLATFORMS", "cpu")
    with open(log_path, "w") as log:
        return subprocess.Popen(
            [sys.executable, "-m", "pinot_tpu.tools.admin", *args],
            stdout=log, stderr=subprocess.STDOUT, env=env)


def _wait_http(url, timeout=60.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            with urllib.request.urlopen(url + "/health", timeout=2) as r:
                if r.status == 200:
                    return True
        except Exception:  # noqa: BLE001
            time.sleep(0.2)
    return False


def _query(url, sql, timeout=120.0):
    req = urllib.request.Request(
        url + "/query/sql",
        data=json.dumps({"sql": sql}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


@pytest.mark.slow
def test_multiprocess_cluster_end_to_end(tmp_path):
    reg = str(tmp_path / "cluster.json")
    port = _free_port()  # stale-broker collisions would poison /health
    schema = Schema.build(name="mp",
                          dimensions=[("k", DataType.STRING)],
                          metrics=[("v", DataType.LONG)])
    schema.save(str(tmp_path / "schema.json"))
    (tmp_path / "table.json").write_text(
        json.dumps(TableConfig(table_name="mp").to_json()))
    data = tmp_path / "files"
    data.mkdir()
    with open(data / "a.csv", "w") as f:
        f.write("k,v\n")
        for i in range(1000):
            f.write(f"k{i % 7},{i}\n")
    (tmp_path / "job.json").write_text(json.dumps({
        "table_name": "mp", "input_dir": str(data)}))

    procs = []
    try:
        procs.append(_spawn(
            ["start-server", "--registry", reg,
             "--data-dir", str(tmp_path / "sd"), "--id", "proc_server"],
            str(tmp_path / "server.log")))
        procs.append(_spawn(
            ["start-broker", "--registry", reg, "--port", str(port),
             "--timeout-s", "120"],
            str(tmp_path / "broker.log")))
        url = f"http://127.0.0.1:{port}"
        assert _wait_http(url), "broker HTTP never came up"

        # table + ingest from THIS process (a third participant)
        assert subprocess.run(
            [sys.executable, "-m", "pinot_tpu.tools.admin", "add-table",
             "--registry", reg, "--schema", str(tmp_path / "schema.json"),
             "--config", str(tmp_path / "table.json"),
             "--deep-store", str(tmp_path / "ds")],
            env={**os.environ, "PYTHONPATH": os.getcwd(),
                 "JAX_PLATFORMS": "cpu"},
            capture_output=True, timeout=60).returncode == 0
        assert subprocess.run(
            [sys.executable, "-m", "pinot_tpu.tools.admin", "ingest",
             "--registry", reg, "--spec", str(tmp_path / "job.json"),
             "--deep-store", str(tmp_path / "ds")],
            env={**os.environ, "PYTHONPATH": os.getcwd(),
                 "JAX_PLATFORMS": "cpu"},
            capture_output=True, timeout=60).returncode == 0

        deadline = time.time() + 90
        rows = None
        while time.time() < deadline:
            try:
                r = _query(url, "SELECT k, SUM(v), COUNT(*) FROM mp "
                                "GROUP BY k ORDER BY k")
                if not r.get("exceptions"):
                    rows = r["resultTable"]["rows"]
                    break
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.5)
        assert rows is not None, "query never succeeded across processes"
        v = np.arange(1000)
        want = [[f"k{i}", int(v[v % 7 == i].sum()), int((v % 7 == i).sum())]
                for i in range(7)]
        assert rows == want
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
