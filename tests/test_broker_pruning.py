"""Broker-side segment pruning: partition + time.

Reference analogs: SinglePartitionColumnSegmentPruner.java,
TimeSegmentPruner.java — the broker drops segments from the scatter set when
the filter provably excludes them, and the response reports
numSegmentsPrunedByBroker.
"""

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import (
    SegmentPartitionConfig,
    TableConfig,
)
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment

from tests.test_cluster import wait_until


@pytest.fixture()
def cluster(tmp_path):
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "deepstore"))
    servers = [
        ServerInstance(f"server_{i}", registry, str(tmp_path / f"srv{i}"),
                       device_executor=None)
        for i in range(2)
    ]
    for s in servers:
        s.start()
    broker = Broker(registry, timeout_s=10.0)
    yield registry, controller, servers, broker
    broker.close()
    for s in servers:
        try:
            s.stop()
        except Exception:
            pass


N_PART = 4


def _partitioned_table(tmp_path, controller, n_segments=N_PART, rows=500):
    """One segment per modulo-partition of `store_id`, plus disjoint time
    ranges per segment on `ts`."""
    schema = Schema.build(
        name="orders",
        dimensions=[("store_id", DataType.INT)],
        metrics=[("amount", DataType.INT)],
        datetimes=[("ts", DataType.LONG)],
    )
    cfg = TableConfig(
        table_name="orders",
        replication=1,
        time_column="ts",
        partition=SegmentPartitionConfig(
            column_partition_map={"store_id": ("modulo", N_PART)}
        ),
    )
    controller.add_table(cfg, schema)
    rng = np.random.default_rng(5)
    all_cols = []
    for i in range(n_segments):
        # store_id values all ≡ i (mod N_PART); ts in [i*1000, i*1000+999]
        cols = {
            "store_id": (rng.integers(0, 100, rows) * N_PART + i).astype(np.int64),
            "amount": rng.integers(1, 100, rows).astype(np.int32),
            "ts": (i * 1000 + rng.integers(0, 1000, rows)).astype(np.int64),
        }
        all_cols.append(cols)
        d = str(tmp_path / f"seg{i}")
        build_segment(schema, cols, d, cfg, f"orders_s{i}")
        controller.upload_segment("orders", d)
    return schema, cfg, all_cols


def _loaded(servers, n):
    # the broker routes on the EXTERNAL VIEW, which a server publishes at
    # the end of its sync tick — waiting on server-local loads alone races
    # one tick ahead of routability
    registry = servers[0].registry
    return lambda: (
        sum(len(s.engine.tables["orders_OFFLINE"].segments)
            if s.engine.tables.get("orders_OFFLINE") else 0
            for s in servers) >= n
        and len(registry.external_view("orders_OFFLINE")) >= n
    )


class TestBrokerPruning:
    def test_partition_pruning_eq(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _, _, all_cols = _partitioned_table(tmp_path, controller)
        assert wait_until(_loaded(servers, N_PART))

        # store_id = 6 → partition 2 → only segment 2 scanned
        r = broker.execute("SELECT SUM(amount) FROM orders WHERE store_id = 6")
        expected = sum(
            int(c["amount"][c["store_id"] == 6].sum()) for c in all_cols
        )
        assert int(float(r["resultTable"]["rows"][0][0])) == expected
        assert r["numSegmentsPrunedByBroker"] == N_PART - 1
        assert r["numSegmentsQueried"] == 1

    def test_partition_pruning_in(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _, _, all_cols = _partitioned_table(tmp_path, controller)
        assert wait_until(_loaded(servers, N_PART))

        # values in partitions {1, 3} → two segments survive
        r = broker.execute(
            "SELECT COUNT(*) FROM orders WHERE store_id IN (5, 7)"
        )
        expected = sum(
            int(np.isin(c["store_id"], [5, 7]).sum()) for c in all_cols
        )
        assert int(r["resultTable"]["rows"][0][0]) == expected
        assert r["numSegmentsPrunedByBroker"] == N_PART - 2

    def test_time_pruning_range(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _, _, all_cols = _partitioned_table(tmp_path, controller)
        assert wait_until(_loaded(servers, N_PART))

        # ts between 1000 and 1999 → only segment 1
        r = broker.execute(
            "SELECT COUNT(*) FROM orders WHERE ts >= 1000 AND ts < 2000"
        )
        expected = sum(
            int(((c["ts"] >= 1000) & (c["ts"] < 2000)).sum()) for c in all_cols
        )
        assert int(r["resultTable"]["rows"][0][0]) == expected
        assert r["numSegmentsPrunedByBroker"] == N_PART - 1
        assert r["numSegmentsQueried"] == 1

    def test_all_pruned_returns_empty(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _partitioned_table(tmp_path, controller)
        assert wait_until(_loaded(servers, N_PART))

        r = broker.execute("SELECT SUM(amount) FROM orders WHERE ts > 999999")
        # one fallback segment queried so the reduce sees a typed result
        assert r["numSegmentsQueried"] == 1
        val = r["resultTable"]["rows"][0][0]
        assert val in (0, 0.0, None, "null")

    def test_or_filter_not_overpruned(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _, _, all_cols = _partitioned_table(tmp_path, controller)
        assert wait_until(_loaded(servers, N_PART))

        # OR across two partitions must keep both segments
        r = broker.execute(
            "SELECT COUNT(*) FROM orders WHERE store_id = 4 OR store_id = 5"
        )
        expected = sum(
            int(np.isin(c["store_id"], [4, 5]).sum()) for c in all_cols
        )
        assert int(r["resultTable"]["rows"][0][0]) == expected
        assert r["numSegmentsPrunedByBroker"] == N_PART - 2

    def test_not_filter_conservative(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _, _, all_cols = _partitioned_table(tmp_path, controller)
        assert wait_until(_loaded(servers, N_PART))

        r = broker.execute("SELECT COUNT(*) FROM orders WHERE NOT store_id = 6")
        expected = sum(int((c["store_id"] != 6).sum()) for c in all_cols)
        assert int(r["resultTable"]["rows"][0][0]) == expected
        assert r["numSegmentsPrunedByBroker"] == 0


class TestValueStatsPruning:
    """Per-column min/max pruning on NON-time columns (SegmentRecord
    column_stats → broker/segment_pruner.py _stats_may_match)."""

    def _value_table(self, tmp_path, controller, servers):
        schema = Schema.build(
            name="sales",
            dimensions=[("region", DataType.STRING)],
            metrics=[("amount", DataType.INT)],
        )
        cfg = TableConfig(table_name="sales", replication=1)
        controller.add_table(cfg, schema)
        rng = np.random.default_rng(9)
        all_cols = []
        for i in range(4):
            # amount ranges are DISJOINT per segment: [i*1000, i*1000+999]
            cols = {
                "region": np.array(["east", "west"])[rng.integers(0, 2, 300)],
                "amount": (i * 1000 + rng.integers(0, 1000, 300)).astype(
                    np.int32),
            }
            cols["amount"][0] = i * 1000        # pin the min
            cols["amount"][1] = i * 1000 + 999  # pin the max
            all_cols.append(cols)
            d = str(tmp_path / f"sseg{i}")
            build_segment(schema, cols, d, cfg, f"sales_s{i}")
            controller.upload_segment("sales", d)
        registry = servers[0].registry

        def loaded():
            return (
                sum(len(s.engine.tables["sales_OFFLINE"].segments)
                    if s.engine.tables.get("sales_OFFLINE") else 0
                    for s in servers) >= 4
                and len(registry.external_view("sales_OFFLINE")) >= 4
            )

        assert wait_until(loaded)
        return all_cols

    def test_range_prunes_by_value(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        all_cols = self._value_table(tmp_path, controller, servers)
        r = broker.execute("SELECT COUNT(*) FROM sales WHERE amount >= 2500")
        expected = sum(int((c["amount"] >= 2500).sum()) for c in all_cols)
        assert int(r["resultTable"]["rows"][0][0]) == expected
        # segments 0 and 1 (amount < 2000) provably cannot match
        assert r["numSegmentsPrunedByBroker"] == 2
        assert r["numSegmentsPrunedByValue"] == 2

    def test_eq_and_in_prune_by_value(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        all_cols = self._value_table(tmp_path, controller, servers)
        r = broker.execute("SELECT COUNT(*) FROM sales WHERE amount = 1500")
        expected = sum(int((c["amount"] == 1500).sum()) for c in all_cols)
        assert int(r["resultTable"]["rows"][0][0]) == expected
        assert r["numSegmentsPrunedByBroker"] == 3
        assert r["numSegmentsPrunedByValue"] == 3

        r = broker.execute(
            "SELECT COUNT(*) FROM sales WHERE amount IN (500, 3500)")
        expected = sum(
            int(np.isin(c["amount"], [500, 3500]).sum()) for c in all_cols)
        assert int(r["resultTable"]["rows"][0][0]) == expected
        assert r["numSegmentsPrunedByBroker"] == 2

    def test_incomparable_literal_conservative(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        all_cols = self._value_table(tmp_path, controller, servers)
        # string literal against int stats: may-match, never mis-pruned
        r = broker.execute("SELECT COUNT(*) FROM sales WHERE region = 'east'")
        expected = sum(int((c["region"] == "east").sum()) for c in all_cols)
        assert int(r["resultTable"]["rows"][0][0]) == expected
        assert r["numSegmentsPrunedByValue"] == 0
