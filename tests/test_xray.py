"""ISSUE 11 — performance X-ray: kernel roofline accounting, EXPLAIN
ANALYZE, and segment-temperature telemetry.

Covers the three tentpole pieces and their satellites:

- the once-per-process HBM peak probe (ops/roofline.py) and the
  per-flight bytes-moved/GB/s accounting the device executor records on
  every fetch (hbm_stats roofline section, per-query response fields);
- ``EXPLAIN ANALYZE`` on single-stage group-bys and multi-stage joins,
  embedded and through a real broker/server cluster — per-node actual
  rows/ms, the per-kernel ``GB/s (x% of HBM peak)`` line, and the
  bit-identical-results contract (``analyzedResponse``);
- the decayed per-segment heat tracker (server/heat.py), its heartbeat
  piggyback, the controller's ``GET /tables/{t}/heat`` aggregation, and
  the ``tools/clusterstat.py`` CLI;
- the Prometheus name sanitizer (legal exposition under
  ``prometheus_client`` for instance/attempt-keyed metrics), the query
  log summarizer's result-cache rate + scatter waterfall slot, and
  ``tools/benchdiff.py``'s detail.roofline diff.
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import Controller, aggregate_heat
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment


def wait_until(cond, timeout=15.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def xray_engine(tmp_path_factory):
    """Embedded engine: a device-eligible fact table plus a dim table for
    join ANALYZE."""
    base = tmp_path_factory.mktemp("xray")
    fact_schema = Schema.build(
        name="xf",
        dimensions=[("k", DataType.STRING)],
        metrics=[("v", DataType.INT)],
    )
    dim_schema = Schema.build(
        name="xd",
        dimensions=[("k", DataType.STRING), ("grp", DataType.STRING)],
        metrics=[],
    )
    eng = QueryEngine()
    rng = np.random.default_rng(7)
    fcfg = TableConfig(table_name="xf")
    for i in range(2):
        cols = {
            "k": np.array(["a", "b", "c", "d"])[rng.integers(0, 4, 8000)],
            "v": rng.integers(0, 50, 8000).astype(np.int32),
        }
        d = str(base / f"f{i}")
        build_segment(fact_schema, cols, d, fcfg, f"xf_s{i}")
        eng.add_segment("xf", ImmutableSegment(d))
    dcfg = TableConfig(table_name="xd", is_dim_table=True)
    dcols = {"k": np.array(["a", "b", "c", "d"]),
             "grp": np.array(["x", "x", "y", "y"])}
    dd = str(base / "d0")
    build_segment(dim_schema, dcols, dd, dcfg, "xd_s0")
    eng.add_segment("xd", ImmutableSegment(dd))
    eng.table("xd").is_dim_table = True
    return eng


GROUPBY_SQL = "SELECT k, COUNT(*), SUM(v) FROM xf GROUP BY k ORDER BY k"
JOIN_SQL = ("SELECT xd.grp, SUM(xf.v) FROM xf JOIN xd ON xf.k = xd.k "
            "GROUP BY xd.grp ORDER BY xd.grp")


# ---------------------------------------------------------------------------
# tentpole 1: kernel roofline accounting
# ---------------------------------------------------------------------------


class TestRooflineProbe:
    def test_probe_positive_and_cached(self):
        from pinot_tpu.ops import roofline

        p1 = roofline.hbm_peak_gbps()
        assert p1 > 0
        assert roofline.hbm_peak_gbps() == p1  # cached, not re-measured
        assert roofline.peak_if_probed() == p1

    def test_env_override(self, monkeypatch):
        from pinot_tpu.ops import roofline

        monkeypatch.setenv("PINOT_TPU_HBM_PEAK_GBPS", "819.0")
        assert roofline.hbm_peak_gbps() == 819.0
        assert roofline.peak_if_probed() == 819.0

    def test_pct_of_peak(self, monkeypatch):
        from pinot_tpu.ops import roofline

        monkeypatch.setenv("PINOT_TPU_HBM_PEAK_GBPS", "800")
        assert roofline.pct_of_peak(8.0) == 1.0
        assert roofline.pct_of_peak(None) is None


class TestRooflineAccounting:
    def test_query_response_carries_roofline(self, xray_engine):
        r = xray_engine.execute(GROUPBY_SQL)
        assert not r.get("exceptions")
        recs = r.get("roofline")
        assert recs, "device query recorded no roofline flight"
        rec = recs[0]
        assert rec["kernel"].startswith("groupby")
        assert rec["bytesMoved"] > 0 or rec["cacheHit"]
        # the per-query stat sums agree with the flight records
        assert r["deviceKernelMs"] >= 0
        assert r["deviceBytesMoved"] == sum(
            x.get("bytesMoved", 0) for x in recs)
        if not rec["cacheHit"]:
            assert rec["gbps"] > 0
            assert rec["pctOfPeak"] > 0
            assert rec["peakGbps"] > 0

    def test_hbm_stats_roofline_section(self, xray_engine):
        xray_engine.execute(GROUPBY_SQL)
        roof = xray_engine.device.hbm_stats()["roofline"]
        assert roof["peak_gbps"] and roof["peak_gbps"] > 0
        kernels = roof["kernels"]
        assert any(k.startswith("groupby") for k in kernels)
        entry = next(v for k, v in kernels.items()
                     if k.startswith("groupby"))
        assert entry["queries"] >= 1
        assert entry["kernel_ms"] >= 0

    def test_kernel_gbps_histogram_feeds_metrics(self, xray_engine):
        from pinot_tpu.common.metrics import get_metrics

        xray_engine.device.partials_cache_enabled = False
        try:
            xray_engine.execute(GROUPBY_SQL)
        finally:
            xray_engine.device.partials_cache_enabled = True
        snap = get_metrics("server").snapshot()
        assert "server.deviceKernelGbps" in snap["histograms"]
        assert snap["histograms"]["server.deviceKernelGbps"]["count"] >= 1

    def test_cache_hit_flights_marked_not_rated(self, xray_engine):
        dev = xray_engine.device
        dev.partials_cache_enabled = True
        xray_engine.execute(GROUPBY_SQL)  # warm / insert
        r = xray_engine.execute(GROUPBY_SQL)  # hit
        if r.get("partialsCacheHit"):
            rec = (r.get("roofline") or [{}])[0]
            assert rec.get("cacheHit") is True
            assert "gbps" not in rec  # no kernel ran: nothing to rate


# ---------------------------------------------------------------------------
# tentpole 2: EXPLAIN ANALYZE
# ---------------------------------------------------------------------------


def _lines(resp):
    return [r[0] for r in resp["resultTable"]["rows"]]


class TestExplainAnalyzeParsing:
    def test_parser_flags(self):
        from pinot_tpu.sql.parser import parse_sql

        stmt = parse_sql("EXPLAIN ANALYZE SELECT * FROM t")
        assert stmt.explain and stmt.analyze
        stmt = parse_sql("EXPLAIN PLAN FOR SELECT * FROM t")
        assert stmt.explain and not stmt.analyze
        stmt = parse_sql("SELECT * FROM t")
        assert not stmt.explain and not stmt.analyze

    def test_strip_preserves_set_prefix(self):
        from pinot_tpu.sql.parser import strip_explain_analyze

        sql = "SET timeoutMs = 5000; EXPLAIN ANALYZE SELECT 1 FROM t"
        assert strip_explain_analyze(sql) == \
            "SET timeoutMs = 5000; SELECT 1 FROM t"
        plain = "SELECT 1 FROM t"
        assert strip_explain_analyze(plain) == plain


class TestExplainAnalyzeEmbedded:
    def test_groupby_renders_actuals_and_kernel_line(self, xray_engine):
        ra = xray_engine.execute("EXPLAIN ANALYZE " + GROUPBY_SQL)
        assert not ra.get("exceptions")
        lines = _lines(ra)
        assert any("(actual: rows=" in ln for ln in lines), lines
        assert any(ln.strip().startswith("ROWS(") for ln in lines)
        assert any(ln.strip().startswith("SEGMENTS(") for ln in lines)
        assert any(ln.strip().startswith("PHASE(") for ln in lines)
        kernel = [ln for ln in lines if "GB/s" in ln]
        assert kernel and any("% of HBM peak" in ln for ln in kernel), lines
        assert any(ln.strip().startswith("CACHE(") for ln in lines)

    def test_results_bit_identical(self, xray_engine):
        plain = xray_engine.execute(GROUPBY_SQL)
        ra = xray_engine.execute("EXPLAIN ANALYZE " + GROUPBY_SQL)
        assert ra["analyzedResponse"]["resultTable"] == plain["resultTable"]

    def test_join_renders_per_node_actuals(self, xray_engine):
        plain = xray_engine.execute(JOIN_SQL)
        assert not plain.get("exceptions")
        ra = xray_engine.execute("EXPLAIN ANALYZE " + JOIN_SQL)
        lines = _lines(ra)
        join_lines = [ln for ln in lines if ln.strip().startswith("JOIN_")]
        assert join_lines and "(actual: out=" in join_lines[0], lines
        scan_lines = [ln for ln in lines if ln.strip().startswith("SCAN(")]
        assert all("(actual: out=" in ln for ln in scan_lines), lines
        assert any("GB/s" in ln and "% of HBM peak" in ln
                   for ln in lines), lines
        # the embedded multistage path fills the waterfall via its
        # thread-local tracer (host_scan + stage2 spans)
        phase = [ln for ln in lines if ln.strip().startswith("PHASE(")]
        assert phase and "stage2=" in phase[0], lines
        # per-table pushdown filters must NOT carry the cluster-wide
        # docsScanned total (single-stage-only annotation)
        assert not any(ln.strip().startswith("FILTER_")
                       and "matched=" in ln for ln in lines), lines
        assert ra["analyzedResponse"]["resultTable"] == plain["resultTable"]

    def test_plain_explain_unchanged(self, xray_engine):
        rp = xray_engine.execute("EXPLAIN PLAN FOR " + GROUPBY_SQL)
        assert not any("ANALYZE" in ln for ln in _lines(rp))


@pytest.fixture()
def xray_cluster(tmp_path):
    """1 broker + 2 servers over a real registry; device executors on
    (the roofline records must cross the wire)."""
    from pinot_tpu.broker.broker import Broker

    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "ds"))
    servers = [
        ServerInstance(f"xsrv_{i}", registry, str(tmp_path / f"x{i}"))
        for i in range(2)
    ]
    for s in servers:
        s.heartbeat_interval_s = 0.3
        s.start()
    schema = Schema.build(
        name="xt",
        dimensions=[("k", DataType.STRING)],
        metrics=[("v", DataType.LONG)],
    )
    cfg = TableConfig(table_name="xt", replication=1)
    controller.add_table(cfg, schema)
    rng = np.random.default_rng(2)
    for i in range(2):
        d = str(tmp_path / f"up{i}")
        build_segment(
            schema,
            {"k": np.array(["a", "b", "c"])[rng.integers(0, 3, 4000)],
             "v": rng.integers(0, 50, 4000).astype(np.int64)},
            d, cfg, f"xt_s{i}")
        controller.upload_segment("xt", d)
    # a replicated dim table so joins route through the broker too
    dim_schema = Schema.build(
        name="xdim",
        dimensions=[("k", DataType.STRING), ("grp", DataType.STRING)],
        metrics=[],
    )
    dcfg = TableConfig(table_name="xdim", replication=1, is_dim_table=True)
    controller.add_table(dcfg, dim_schema)
    dd = str(tmp_path / "updim")
    build_segment(dim_schema,
                  {"k": np.array(["a", "b", "c"]),
                   "grp": np.array(["x", "x", "y"])},
                  dd, dcfg, "xdim_s0")
    controller.upload_segment("xdim", dd)
    assert wait_until(lambda: len(registry.external_view("xt_OFFLINE")) == 2)
    assert wait_until(
        lambda: len(registry.external_view("xdim_OFFLINE")) == 1)
    broker = Broker(registry, timeout_s=30.0)
    yield registry, servers, broker
    broker.close()
    for s in servers:
        try:
            s.stop(drain_timeout_s=0.2)
        except Exception:  # noqa: BLE001
            pass


CLUSTER_SQL = "SELECT k, SUM(v) FROM xt GROUP BY k ORDER BY k"


class TestExplainAnalyzeCluster:
    def test_broker_explain_analyze(self, xray_cluster):
        _registry, _servers, broker = xray_cluster
        broker.execute(CLUSTER_SQL)  # warm the templates
        plain = broker.execute(CLUSTER_SQL)
        assert not plain.get("exceptions")
        ra = broker.execute("EXPLAIN ANALYZE " + CLUSTER_SQL)
        assert not ra.get("exceptions"), ra
        lines = _lines(ra)
        # per-instance kernel lines with the %-of-peak annotation
        kernel = [ln for ln in lines if "GB/s" in ln]
        assert kernel and any("% of HBM peak" in ln for ln in kernel), lines
        assert any("@xsrv_" in ln for ln in kernel), kernel
        # the phase waterfall came from the merged per-server traceInfo
        assert any(ln.strip().startswith("PHASE(") for ln in lines), lines
        assert ra["analyzedResponse"]["resultTable"] == \
            plain["resultTable"]

    def test_broker_multistage_explain_analyze(self, xray_cluster):
        """Regression: the multistage traceInfo nests per-leaf dicts —
        annotate_analyze's waterfall must recurse them, not crash into
        a generic 450 (phase_breakdown used to assume span lists)."""
        _registry, _servers, broker = xray_cluster
        jsql = ("SELECT xdim.grp, SUM(xt.v) FROM xt "
                "JOIN xdim ON xt.k = xdim.k "
                "GROUP BY xdim.grp ORDER BY xdim.grp")
        plain = broker.execute(jsql)
        assert not plain.get("exceptions"), plain
        ra = broker.execute("EXPLAIN ANALYZE " + jsql)
        assert not ra.get("exceptions"), ra
        lines = _lines(ra)
        # STAGE_2 actual-in is the JOINED row count, not the leaf docs
        stage2 = next(ln for ln in lines
                      if ln.strip().startswith("STAGE_2_"))
        n_joined = ra["analyzedResponse"]["numJoinedRows"]
        assert f"in={n_joined} rows" in stage2, stage2
        assert any(ln.strip().startswith("PHASE(") for ln in lines), lines
        assert any("GB/s" in ln for ln in lines), lines
        assert ra["analyzedResponse"]["resultTable"] == \
            plain["resultTable"]

    def test_server_partials_ship_roofline_records(self, xray_cluster):
        _registry, _servers, broker = xray_cluster
        r = broker.execute(
            "SET usePartialsCache = false; " + CLUSTER_SQL)
        assert not r.get("exceptions")
        recs = r.get("roofline") or []
        assert recs, "scattered query shipped no roofline records"
        assert all("instance" in rec for rec in recs)
        assert r.get("deviceBytesMoved", 0) > 0


# ---------------------------------------------------------------------------
# tentpole 3: segment-temperature telemetry
# ---------------------------------------------------------------------------


class TestHeatTracker:
    def test_note_and_decay(self):
        from pinot_tpu.server.heat import SegmentHeatTracker

        h = SegmentHeatTracker(half_life_s=10.0)
        t0 = 1000.0
        h.note("t", "s0", bytes_scanned=100, now=t0)
        h.note("t", "s0", bytes_scanned=100, now=t0)
        snap = h.snapshot(now=t0)["t"]["s0"]
        assert snap["accesses"] == 2 and snap["bytes"] == 200
        assert snap["rate"] == pytest.approx(2.0)
        # one half-life later the decayed rate halves; totals persist
        snap2 = h.snapshot(now=t0 + 10.0)["t"]["s0"]
        assert snap2["rate"] == pytest.approx(1.0, rel=1e-3)
        assert snap2["accesses"] == 2

    def test_top_per_table_cap_keeps_hottest(self):
        from pinot_tpu.server.heat import SegmentHeatTracker

        h = SegmentHeatTracker(half_life_s=60.0)
        t0 = 1000.0
        for i in range(6):
            for _ in range(i + 1):  # s5 hottest
                h.note("t", f"s{i}", now=t0)
        snap = h.snapshot(top_per_table=2, now=t0)["t"]
        assert set(snap) == {"s5", "s4"}

    def test_entry_bound_evicts_lru(self):
        from pinot_tpu.server.heat import SegmentHeatTracker

        h = SegmentHeatTracker(max_entries=16)
        for i in range(40):
            h.note("t", f"s{i}", now=1000.0 + i)
        assert h.size() == 16

    def test_aggregate_heat_merges_instances(self):
        from pinot_tpu.cluster.registry import InstanceInfo, Role

        registry = ClusterRegistry()
        for i in range(2):
            info = InstanceInfo(f"hsrv_{i}", Role.SERVER)
            info.heat = {"ht_OFFLINE": {
                "seg_a": {"rate": 1.5, "bytesRate": 10.0, "accesses": 3,
                          "bytes": 30, "lastAccessTs": 100.0 + i}}}
            registry.register_instance(info)
        agg = aggregate_heat(registry, "ht")
        assert agg["instancesReporting"] == 2
        seg = agg["segments"]["seg_a"]
        assert seg["rate"] == pytest.approx(3.0)
        assert seg["accesses"] == 6
        assert seg["instances"] == 2
        assert seg["lastAccessTs"] == 101.0

    def test_cluster_heartbeat_and_endpoint(self, xray_cluster, tmp_path):
        from pinot_tpu.controller.http_api import ControllerHttpServer

        registry, servers, broker = xray_cluster
        for _ in range(3):
            assert not broker.execute(CLUSTER_SQL).get("exceptions")
        # the heartbeat piggyback lands within the (shortened) cadence
        assert wait_until(
            lambda: aggregate_heat(registry, "xt").get("segments"),
            timeout=10.0), "no heat reported via heartbeats"
        agg = aggregate_heat(registry, "xt")
        assert agg["instancesReporting"] >= 1
        seg = next(iter(agg["segments"].values()))
        assert seg["accesses"] >= 1 and seg["bytes"] > 0
        # the controller REST face (GET /tables/{t}/heat)
        http = ControllerHttpServer(registry)
        http.start()
        try:
            with urllib.request.urlopen(
                    http.url + "/tables/xt/heat", timeout=10) as resp:
                doc = json.loads(resp.read().decode())
            assert doc["segments"], doc
            # the clusterstat CLI renders the same payload
            from pinot_tpu.tools import clusterstat

            out = clusterstat.render(clusterstat.gather(
                http.url, table="xt"))
            assert "xt" in out and "rate=" in out
            assert clusterstat.main([http.url, "--table", "xt",
                                     "--json"]) == 0
            # a table literally named "heat" keeps its metadata route:
            # GET /tables/heat must NOT be shadowed into an aggregation
            # over the empty table name
            req = urllib.request.Request(http.url + "/tables/heat")
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    doc2 = json.loads(resp.read().decode())
            except urllib.error.HTTPError as e:
                assert e.code == 404  # no table named "heat" registered
            else:
                assert "instancesReporting" not in doc2
        finally:
            http.stop()


# ---------------------------------------------------------------------------
# satellites: prometheus sanitization, summarizer, benchdiff
# ---------------------------------------------------------------------------


class TestPrometheusSanitize:
    def test_nasty_keys_round_trip_under_prometheus_client(self):
        from prometheus_client.parser import text_string_to_metric_families

        from pinot_tpu.common.metrics import MetricsRegistry

        reg = MetricsRegistry("bro ker")
        reg.gauge("latency", 1.5, tag="inst (retry)")
        reg.count("queries", 2, tag="inst (hedge)")
        reg.time_ms("serverLatencyMs", 12.0, tag="t.x-y (retry)")
        text = reg.prometheus_text()
        fams = list(text_string_to_metric_families(text))
        names = {f.name for f in fams}
        assert any("inst__retry_" in n for n in names), names
        # every emitted name is legal
        import re

        legal = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
        for f in fams:
            for s in f.samples:
                assert legal.match(s.name), s.name

    def test_sanitize_function(self):
        from pinot_tpu.common.metrics import sanitize

        assert sanitize("a.b-c d(e)") == "pinot_tpu_a_b_c_d_e_"

    def test_reset_metrics_clears_roofline_histograms(self):
        from pinot_tpu.common.metrics import get_metrics, reset_metrics

        m = get_metrics("xraytest")
        m.observe("deviceKernelGbps", 3.0)
        m.gauge("hbmPeakGbps", 10.0, tag="i0")
        assert m.snapshot()["histograms"]
        reset_metrics("xraytest")
        snap = m.snapshot()
        assert not snap["histograms"] and not snap["gauges"]


class TestQuerylogSummarizer:
    def _entry(self, tpl, ms, partials=False, result=False):
        return {"template": tpl, "timeUsedMs": ms,
                "counters": {"partialsCacheHit": partials,
                             "resultCacheHit": result}}

    def test_per_template_result_cache_rate(self):
        from pinot_tpu.tools.querylog import summarize

        entries = [self._entry("t1", 10.0, result=True),
                   self._entry("t1", 12.0, result=False),
                   self._entry("t1", 11.0, partials=True)]
        s = summarize(entries, per_template=True)
        row = s["templates"]["t1"]
        assert row["resultCacheHitRate"] == pytest.approx(1 / 3, abs=1e-3)
        assert row["cacheHitRate"] == pytest.approx(1 / 3, abs=1e-3)

    def test_phase_breakdown_recurses_multistage_nesting(self):
        """Multistage entries nest leaf traceInfo dicts under
        ``leaf:<alias>`` keys — the waterfall must recurse, not crash."""
        from pinot_tpu.tools.querylog import phase_breakdown

        entry = {"traceInfo": {"leaf:f": {
            "srv_0": [{"phase": "server.compile", "startMs": 0,
                       "durationMs": 2.0}],
            "broker": [{"phase": "broker.reduce", "startMs": 0,
                        "durationMs": 1.5}],
        }}}
        phases = phase_breakdown(entry)
        assert phases.get("compile") == pytest.approx(2.0)
        assert phases.get("reduce") == pytest.approx(1.5)

    def test_waterfall_includes_broker_scatter(self):
        from pinot_tpu.tools.querylog import phase_breakdown

        entry = {"traceInfo": {"broker": [
            {"phase": "broker.scatter_gather", "startMs": 0,
             "durationMs": 7.5},
            {"phase": "broker.reduce", "startMs": 8, "durationMs": 1.0},
        ]}}
        phases = phase_breakdown(entry)
        assert phases.get("scatter") == pytest.approx(7.5)
        assert phases.get("reduce") == pytest.approx(1.0)


class TestBenchdiffRoofline:
    OLD = {"roofline": {"peak_gbps": 800.0, "kernels": {
        "groupby": {"gbps": 10.0}, "groupby+bskip": {"gbps": 5.0}}}}

    def test_regression_detected(self):
        from pinot_tpu.tools.benchdiff import diff_rounds

        new = {"roofline": {"peak_gbps": 800.0, "kernels": {
            "groupby": {"gbps": 5.0},         # -50%: regression
            "groupby+bskip": {"gbps": 5.1}}}}  # within threshold
        rep = diff_rounds(self.OLD, new, threshold=0.25)
        assert "roofline.groupby.gbps" in rep["regressions"]
        assert "roofline.groupby+bskip.gbps" in rep["unchanged"]

    def test_nested_observability_fallback(self):
        from pinot_tpu.tools.benchdiff import extract_metrics

        nested = {"observability": {"roofline": {
            "kernels": {"groupby": {"gbps": 9.0}}}}}
        assert extract_metrics(nested)[
            "roofline.groupby.gbps"] == (9.0, "higher")

    def test_missing_section_is_added_not_regression(self):
        from pinot_tpu.tools.benchdiff import diff_rounds

        rep = diff_rounds({}, self.OLD, threshold=0.25)
        assert not rep["regressions"]
        assert "roofline.groupby.gbps" in rep["added"]
