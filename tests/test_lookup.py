"""Dimension tables + LOOKUP transform.

Reference analogs: DimensionTableDataManager (in-memory pk->row map on
every server), LookupTransformFunction, isDimTable replication.
"""

import time

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment


def _dim_schema():
    return Schema.build(
        name="teams",
        dimensions=[("teamID", DataType.STRING), ("teamName", DataType.STRING),
                    ("founded", DataType.INT)],
        primary_key_columns=["teamID"],
    )


def _fact_schema():
    return Schema.build(
        name="games",
        dimensions=[("team", DataType.STRING)],
        metrics=[("score", DataType.INT)],
    )


DIM = {
    "teamID": np.asarray(["t1", "t2", "t3"], dtype=np.str_),
    "teamName": np.asarray(["Tigers", "Bears", "Hawks"], dtype=np.str_),
    "founded": np.asarray([1901, 1950, 1988], dtype=np.int32),
}
FACT = {
    "team": np.asarray(["t1", "t2", "t1", "t9"], dtype=np.str_),
    "score": np.asarray([3, 5, 7, 2], dtype=np.int32),
}


class TestEmbeddedLookup:
    @pytest.fixture()
    def engine(self, tmp_path):
        eng = QueryEngine(device_executor=None)
        dim = build_segment(_dim_schema(), DIM, str(tmp_path / "dim"),
                            TableConfig(table_name="teams", is_dim_table=True), "d0")
        fact = build_segment(_fact_schema(), FACT, str(tmp_path / "fact"),
                             TableConfig(table_name="games"), "f0")
        eng.add_segment("teams", dim)
        eng.add_segment("games", fact)
        return eng

    def test_lookup_select(self, engine):
        r = engine.execute(
            "SELECT team, LOOKUP('teams', 'teamName', 'teamID', team), score "
            "FROM games ORDER BY score")
        assert r["resultTable"]["rows"] == [
            ["t9", "", 2], ["t1", "Tigers", 3], ["t2", "Bears", 5],
            ["t1", "Tigers", 7]]

    def test_lookup_group_by(self, engine):
        r = engine.execute(
            "SELECT LOOKUP('teams', 'teamName', 'teamID', team), SUM(score) "
            "FROM games WHERE team <> 't9' "
            "GROUP BY LOOKUP('teams', 'teamName', 'teamID', team) "
            "ORDER BY LOOKUP('teams', 'teamName', 'teamID', team)")
        assert r["resultTable"]["rows"] == [["Bears", 5], ["Tigers", 10]]

    def test_lookup_numeric_value_and_filter(self, engine):
        # misses yield the value column's type default (0), matching the
        # framework-wide defaults-flow-through null convention — so the t9
        # row (default 0 < 1950) matches alongside the two t1 rows
        r = engine.execute(
            "SELECT COUNT(*) FROM games "
            "WHERE LOOKUP('teams', 'founded', 'teamID', team) < 1950")
        assert r["resultTable"]["rows"][0][0] == 3
        r = engine.execute(
            "SELECT COUNT(*) FROM games WHERE "
            "LOOKUP('teams', 'founded', 'teamID', team) < 1950 AND team <> 't9'")
        assert r["resultTable"]["rows"][0][0] == 2

    def test_cache_invalidated_on_new_segment(self, engine, tmp_path):
        assert engine.execute(
            "SELECT LOOKUP('teams', 'teamName', 'teamID', team) FROM games "
            "WHERE team = 't9'")["resultTable"]["rows"] == [[""]]
        extra = build_segment(
            _dim_schema(),
            {"teamID": np.asarray(["t9"], dtype=np.str_),
             "teamName": np.asarray(["Lions"], dtype=np.str_),
             "founded": np.asarray([2020], dtype=np.int32)},
            str(tmp_path / "dim2"),
            TableConfig(table_name="teams", is_dim_table=True), "d1")
        engine.add_segment("teams", extra)
        assert engine.execute(
            "SELECT LOOKUP('teams', 'teamName', 'teamID', team) FROM games "
            "WHERE team = 't9'")["resultTable"]["rows"] == [["Lions"]]

    def test_missing_dim_table_errors(self, engine):
        r = engine.execute(
            "SELECT LOOKUP('nope', 'a', 'b', team) FROM games")
        assert r["exceptions"]

    def test_literal_key(self, engine):
        # scalar keys broadcast, not iterate character-wise (r3 review)
        r = engine.execute(
            "SELECT LOOKUP('teams', 'teamName', 'teamID', 't1'), score "
            "FROM games ORDER BY score LIMIT 2")
        assert r["resultTable"]["rows"] == [["Tigers", 2], ["Tigers", 3]]

    def test_empty_dim_table_numeric_default(self, tmp_path):
        # empty dim table keeps the value column's numeric type default
        # instead of '' (r3 review)
        eng = QueryEngine(device_executor=None)
        empty = build_segment(
            _dim_schema(),
            {"teamID": np.asarray([], dtype=np.str_),
             "teamName": np.asarray([], dtype=np.str_),
             "founded": np.asarray([], dtype=np.int32)},
            str(tmp_path / "dim"),
            TableConfig(table_name="teams", is_dim_table=True), "d0")
        fact = build_segment(_fact_schema(), FACT, str(tmp_path / "fact"),
                             TableConfig(table_name="games"), "f0")
        eng.add_segment("teams", empty)
        eng.add_segment("games", fact)
        r = eng.execute(
            "SELECT SUM(LOOKUP('teams', 'founded', 'teamID', team)) FROM games")
        assert not r.get("exceptions"), r
        assert r["resultTable"]["rows"][0][0] == 0

    def test_non_dim_table_rejected_when_flagged(self, engine):
        engine.tables["teams"].is_dim_table = False
        try:
            r = engine.execute(
                "SELECT LOOKUP('teams', 'teamName', 'teamID', team) FROM games")
            assert r["exceptions"]
            assert "not a dimension table" in r["exceptions"][0]["message"]
        finally:
            engine.tables["teams"].is_dim_table = None


def wait_until(cond, timeout=15.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


class TestClusterDimTable:
    def test_dim_table_replicates_to_all_servers(self, tmp_path):
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        servers = [
            ServerInstance(f"server_{i}", registry, str(tmp_path / f"s{i}"),
                           device_executor=None)
            for i in range(2)
        ]
        for s in servers:
            s.start()
        broker = Broker(registry, timeout_s=10.0)
        try:
            dim_cfg = TableConfig(table_name="teams", is_dim_table=True)
            controller.add_table(dim_cfg, _dim_schema())
            build_segment(_dim_schema(), DIM, str(tmp_path / "dup"), dim_cfg, "d0")
            controller.upload_segment("teams", str(tmp_path / "dup"))

            fact_cfg = TableConfig(table_name="games")
            controller.add_table(fact_cfg, _fact_schema())
            build_segment(_fact_schema(), FACT, str(tmp_path / "fup"),
                          fact_cfg, "f0")
            controller.upload_segment("games", str(tmp_path / "fup"))

            # dim segment assigned to BOTH servers despite replication=1
            assert wait_until(
                lambda: len(registry.assignment("teams_OFFLINE").get("d0", [])) == 2)
            assert wait_until(
                lambda: len(registry.external_view("games_OFFLINE")) == 1)
            assert wait_until(lambda: all(
                "teams_OFFLINE" in s.engine.tables
                and s.engine.tables["teams_OFFLINE"].segments
                for s in servers))

            r = broker.execute(
                "SELECT LOOKUP('teams', 'teamName', 'teamID', team), SUM(score) "
                "FROM games GROUP BY LOOKUP('teams', 'teamName', 'teamID', team) "
                "ORDER BY SUM(score) DESC")
            assert not r.get("exceptions"), r
            assert r["resultTable"]["rows"][0] == ["Tigers", 10]

            # a server joining AFTER the dim upload gets the dim segments
            # via the controller's periodic replication repair (r3 review)
            late = ServerInstance("server_late", registry,
                                  str(tmp_path / "slate"), device_executor=None)
            late.start()
            servers.append(late)
            assert controller.run_dim_table_replication() == ["teams_OFFLINE"]
            assert wait_until(
                lambda: len(registry.assignment("teams_OFFLINE").get("d0", [])) == 3)
            assert wait_until(
                lambda: "teams_OFFLINE" in late.engine.tables
                and late.engine.tables["teams_OFFLINE"].segments)
        finally:
            broker.close()
            for s in servers:
                s.stop()
