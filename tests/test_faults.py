"""Failure-domain chaos suite (ISSUE 6).

The contract under test: under every injected fault — transport drop,
slow replica, blackholed replica, mid-query server crash, device launch /
fetch failure, chunklet-promotion failure — a query returns either the
CORRECT full result or a correctly-flagged ``partialResult`` with honest
stats (never a hang, a wrong answer, or an unflagged partial), and a
query whose deadline expires comes back as a typed QUERY_TIMEOUT
(errorCode 250) within deadline + 1 s. Plus the broker FailureDetector's
half-open circuit-breaker state machine and the device executor's
quarantine breaker routing a poisoned template to host while other
templates keep running on device.
"""

import time

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker, FailureDetector, LatencyTracker
from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common import faults
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.deadline import Deadline, QueryTimeout
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    yield
    faults.clear()


def wait_until(cond, timeout=10.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# fault registry + deadline primitives
# ---------------------------------------------------------------------------


class TestFaultRegistry:
    def test_inactive_by_default_and_zero_when_cleared(self):
        assert faults.ACTIVE is False
        f = faults.install(faults.Fault(point="p", mode="error"))
        assert faults.ACTIVE is True
        faults.clear()
        assert faults.ACTIVE is False
        # cleared: inject is a no-op even for the old point
        faults.inject("p")
        assert f.fired == 0

    def test_error_delay_and_times(self):
        f = faults.install(faults.Fault(point="p", mode="error", times=2))
        for _ in range(2):
            with pytest.raises(faults.FaultInjected):
                faults.inject("p")
        faults.inject("p")  # disarmed after 2 firings
        assert f.fired == 2
        faults.clear()
        faults.install(faults.Fault(point="d", mode="delay", delay_ms=30))
        t0 = time.perf_counter()
        faults.inject("d")
        assert time.perf_counter() - t0 >= 0.025

    def test_target_substring_match(self):
        faults.install(faults.Fault(point="p", target="server_1",
                                    mode="error"))
        faults.inject("p", target="server_2")  # no match
        with pytest.raises(faults.FaultInjected):
            faults.inject("p", target="server_1")

    def test_blackhole_bounded_by_caller_deadline(self):
        faults.install(faults.Fault(point="p", mode="blackhole",
                                    delay_ms=60_000))
        t0 = time.perf_counter()
        with pytest.raises(faults.FaultInjected):
            faults.inject("p", bound_ms=50)
        assert time.perf_counter() - t0 < 1.0

    def test_parse_spec(self):
        fs = faults.parse_spec(
            "transport.submit@server_1=blackhole:500;"
            "device.launch=error#2; chunklet.promote=delay:10")
        assert [f.point for f in fs] == [
            "transport.submit", "device.launch", "chunklet.promote"]
        assert fs[0].target == "server_1" and fs[0].delay_ms == 500
        assert fs[1].times == 2 and fs[1].target is None
        assert fs[2].mode == "delay"

    def test_device_points_raise_device_error(self):
        faults.install(faults.Fault(point="device.launch", mode="error"))
        with pytest.raises(faults.InjectedDeviceError):
            faults.inject("device.launch")


class TestDeadline:
    def test_remaining_and_expiry(self):
        dl = Deadline(0.05)
        assert not dl.expired()
        assert 0 < dl.remaining_s() <= 0.05
        assert dl.clamp(10.0) <= 0.05
        time.sleep(0.06)
        assert dl.expired()
        assert dl.clamp(10.0) == 0.0
        with pytest.raises(QueryTimeout, match="QUERY_TIMEOUT at here"):
            dl.check("here")


# ---------------------------------------------------------------------------
# FailureDetector state machine (satellite)
# ---------------------------------------------------------------------------


class TestFailureDetectorStateMachine:
    def test_failure_backoff_halfopen_probe_recovery(self):
        fd = FailureDetector(initial_backoff_s=0.1, max_backoff_s=1.0)
        assert fd.state("s") == FailureDetector.ST_HEALTHY
        assert fd.is_healthy("s")

        fd.mark_failure("s")
        assert fd.state("s") == FailureDetector.ST_OPEN
        assert not fd.is_healthy("s")
        assert not fd.try_probe("s")  # window not yet open

        assert wait_until(
            lambda: fd.state("s") == FailureDetector.ST_HALF_OPEN, 1.0)
        assert fd.is_healthy("s")  # routable: the query IS the probe
        assert fd.try_probe("s")   # first caller claims the probe slot
        assert not fd.try_probe("s")  # single probe per window

        fd.mark_success("s")  # probe succeeded
        assert fd.state("s") == FailureDetector.ST_HEALTHY
        assert fd.try_probe("s")  # healthy: not a probe at all

    def test_probe_failure_doubles_backoff(self):
        fd = FailureDetector(initial_backoff_s=0.05, max_backoff_s=10.0)
        fd.mark_failure("s")
        first_backoff = fd._unhealthy["s"][1]
        assert wait_until(
            lambda: fd.state("s") == FailureDetector.ST_HALF_OPEN, 1.0)
        assert fd.try_probe("s")
        fd.mark_failure("s")  # probe failed → OPEN again, doubled
        assert fd.state("s") == FailureDetector.ST_OPEN
        assert fd._unhealthy["s"][1] == pytest.approx(first_backoff * 2)

    def test_backoff_caps_at_max(self):
        fd = FailureDetector(initial_backoff_s=1.0, max_backoff_s=2.0)
        for _ in range(6):
            fd.mark_failure("s")
        assert fd._unhealthy["s"][1] <= 2.0


class TestLatencyTracker:
    def test_p90_and_default(self):
        # ISSUE 7: the tracker reads the SHARED metrics histogram (one
        # latency truth with /metrics) instead of a private ring — a
        # fresh registry isolates the test from other brokers' samples
        from pinot_tpu.common.metrics import MetricsRegistry

        lt = LatencyTracker(default_s=0.07,
                            registry=MetricsRegistry("lt_test"))
        assert lt.p90_s("x") == 0.07  # no samples
        for v in range(100):
            lt.record("x", v / 1000.0)
        # log-bucketed histogram p90 over 0..99 ms (~19% bucket width)
        p90 = lt.p90_s("x")
        assert 0.075 <= p90 <= 0.11


# ---------------------------------------------------------------------------
# cluster-level chaos: transport faults, crash, deadline, partial results
# ---------------------------------------------------------------------------


@pytest.fixture()
def cluster(tmp_path):
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "deepstore"))
    servers = [
        ServerInstance(f"server_{i}", registry, str(tmp_path / f"srv{i}"),
                       device_executor=None)
        for i in range(3)
    ]
    for s in servers:
        s.start()
    broker = Broker(registry, timeout_s=10.0)
    yield registry, controller, servers, broker
    faults.clear()
    broker.close()
    for s in servers:
        try:
            s.stop(drain_timeout_s=0.5)
        except Exception:
            pass


def _push_table(tmp_path, controller, registry, n_segments=4, rows=2000,
                replication=3):
    schema = Schema.build(
        name="sales",
        dimensions=[("region", DataType.STRING)],
        metrics=[("amount", DataType.INT)],
    )
    cfg = TableConfig(table_name="sales", replication=replication)
    controller.add_table(cfg, schema)
    rng = np.random.default_rng(11)
    total = 0
    for i in range(n_segments):
        amounts = rng.integers(1, 500, rows).astype(np.int32)
        total += int(amounts.sum())
        cols = {
            "region": np.array(["na", "eu", "apac"])[
                rng.integers(0, 3, rows)],
            "amount": amounts,
        }
        d = str(tmp_path / f"up_s{i}")
        build_segment(schema, cols, d, cfg, f"sales_s{i}")
        controller.upload_segment("sales", d)
    assert wait_until(
        lambda: all(
            len(insts) >= min(replication, 3)
            for insts in registry.external_view("sales_OFFLINE").values())
        and len(registry.external_view("sales_OFFLINE")) == n_segments)
    return total, n_segments * rows


SQL = "SELECT COUNT(*), SUM(amount) FROM sales"


class TestTransportFaults:
    def test_drop_recovers_via_replica_retry(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        total, n_rows = _push_table(tmp_path, controller, registry)
        # drop the first RPC to one instance: the broker must re-send that
        # segment list to a replica and return a COMPLETE result
        faults.install(faults.Fault(point="transport.submit",
                                    target="server_1", mode="error",
                                    times=1))
        r = broker.execute(SQL)
        assert r.get("exceptions") == [], r
        assert r.get("partialResult") is False
        assert r["resultTable"]["rows"][0] == [n_rows, total]
        # retry attempts count into numServersQueried; everything answered
        assert r["numServersQueried"] >= r["numServersResponded"] >= 1

    def test_slow_replica_still_correct(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        total, n_rows = _push_table(tmp_path, controller, registry)
        faults.install(faults.Fault(point="transport.submit",
                                    target="server_2", mode="delay",
                                    delay_ms=200))
        r = broker.execute(SQL)
        assert r.get("exceptions") == [], r
        assert r["resultTable"]["rows"][0] == [n_rows, total]

    def test_blackhole_with_hedging_zero_errors(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        total, n_rows = _push_table(tmp_path, controller, registry)
        faults.install(faults.Fault(point="transport.submit",
                                    target="server_0", mode="blackhole"))
        for _ in range(3):
            r = broker.execute(f"SET useHedging = true; {SQL}")
            assert r.get("exceptions") == [], r
            assert r["resultTable"]["rows"][0] == [n_rows, total]

    def test_unrecoverable_failure_flags_partial(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        total, n_rows = _push_table(tmp_path, controller, registry)
        # EVERY instance drops the RPC once and retries are dropped too:
        # the response must be a flagged partial (or all-failed error),
        # never an unflagged wrong answer
        faults.install(faults.Fault(point="transport.submit", mode="error"))
        try:
            r = broker.execute(SQL)
        except ConnectionError:
            return  # all servers failed: surfaced loudly — acceptable
        if r.get("exceptions"):
            assert r.get("partialResult") in (True, None) or \
                r.get("resultTable") is None
        else:  # pool raced a success through: must then be complete
            assert r["resultTable"]["rows"][0] == [n_rows, total]


class TestServerCrashMidQuery:
    def test_crash_recovers_on_replica(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        total, n_rows = _push_table(tmp_path, controller, registry)
        # the crash fires mid-query (segments acquired) and kills the RPC
        # at the transport level; the broker retries on replicas
        faults.install(faults.Fault(point="server.crash",
                                    target="server_1", mode="crash",
                                    times=1))
        r = broker.execute(SQL)
        assert r.get("exceptions") == [], r
        assert r["resultTable"]["rows"][0] == [n_rows, total]

    def test_crash_leaves_server_consistent(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        total, n_rows = _push_table(tmp_path, controller, registry)
        faults.install(faults.Fault(point="server.crash", mode="crash",
                                    times=3))
        broker.execute(SQL)  # every replica "crashes" (partial/failed)
        faults.clear()
        # the crash path released segment refs and scheduler slots: the
        # same servers answer the next query completely
        r = broker.execute(SQL)
        assert r.get("exceptions") == [], r
        assert r["resultTable"]["rows"][0] == [n_rows, total]


class TestDeadlinePropagation:
    def test_expired_deadline_returns_250_within_grace(self, cluster,
                                                       tmp_path):
        registry, controller, servers, broker = cluster
        _push_table(tmp_path, controller, registry)
        # every replica sits on the RPC for 2 s against a 300 ms budget
        faults.install(faults.Fault(point="transport.submit", mode="delay",
                                    delay_ms=2000))
        t0 = time.perf_counter()
        r = broker.execute(f"SET timeoutMs = 300; {SQL}")
        elapsed = time.perf_counter() - t0
        assert elapsed < 0.3 + 1.0, elapsed  # deadline + 1 s, never a hang
        assert r.get("exceptions"), r
        assert all(x["errorCode"] == 250 for x in r["exceptions"]), r
        assert r.get("partialResult") is True

    def test_wire_carries_remaining_budget(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _push_table(tmp_path, controller, registry)
        import json

        from pinot_tpu.transport.grpc_transport import QueryRouterChannel

        seen = []
        orig = QueryRouterChannel.submit

        def spy(self, payload, timeout_s):
            seen.append(json.loads(payload.decode()).get("timeoutMs"))
            return orig(self, payload, timeout_s)

        QueryRouterChannel.submit = spy
        try:
            r = broker.execute(f"SET timeoutMs = 5000; {SQL}")
            assert not r.get("exceptions"), r
        finally:
            QueryRouterChannel.submit = orig
        assert seen and all(v is not None and 0 < v <= 5000 for v in seen)

    def test_server_side_timeout_is_typed(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _push_table(tmp_path, controller, registry)
        # an ALREADY-expired budget on the wire: the server must answer
        # the typed in-band QUERY_TIMEOUT, not execute
        import json

        from pinot_tpu.engine.datatable import QueryTimeoutError, decode
        from pinot_tpu.transport.grpc_transport import make_instance_request

        server = servers[0]
        segs = [s for t in server.engine.tables.values()
                for s in t.segments][:1]
        assert segs
        # the server starts its own clock at receive, so a tiny budget
        # alone races execution speed (a cached compile over a small
        # segment can legitimately finish inside 1 ms). Exhaust the
        # compile semaphore instead: the submit provably waits at a
        # deadline-checked seam until its 50 ms budget expires.
        held = 0
        while server._compile_sem.acquire(blocking=False):
            held += 1
        assert held > 0
        try:
            payload = make_instance_request(
                SQL, segs, 1, "b", table="sales_OFFLINE", timeout_ms=50.0)
            out = server._handle_submit(payload)
        finally:
            for _ in range(held):
                server._compile_sem.release()
        with pytest.raises(QueryTimeoutError):
            decode(out)
        assert json.loads(out[4:])["kind"] == "query_timeout"


class TestPartialResultContract:
    def test_dead_server_partial_with_honest_counts(self, tmp_path):
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        servers = [
            ServerInstance(f"server_{i}", registry, str(tmp_path / f"s{i}"),
                           device_executor=None)
            for i in range(3)
        ]
        for s in servers:
            s.start()
        broker = Broker(registry, timeout_s=5.0)
        try:
            total, n_rows = _push_table(tmp_path, controller, registry,
                                        replication=1)
            # hard-kill one server (transport gone, registry entry stays):
            # with replication=1 its segments are unrecoverable
            victim = servers[1]
            victim.transport.stop()
            r = broker.execute(SQL)
            assert r.get("partialResult") is True
            assert r["exceptions"], r
            assert all(x["errorCode"] in (427, 250) for x in r["exceptions"])
            # honest counts: every instance we dispatched to vs the ones
            # whose answers the reduce used
            assert r["numServersQueried"] == 3
            assert r["numServersResponded"] == 2
            # honest data: fewer rows than the full table, flagged partial
            assert r["resultTable"]["rows"][0][0] < n_rows
        finally:
            broker.close()
            for s in servers:
                try:
                    s.stop(drain_timeout_s=0.2)
                except Exception:
                    pass

    def test_shutting_down_server_is_retried(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        total, n_rows = _push_table(tmp_path, controller, registry)
        # flip one server into drain mode WITHOUT stopping transport: new
        # submits get SERVER_SHUTTING_DOWN, which the broker treats as
        # retriable — the query must come back complete via replicas
        servers[2]._shutting_down = True
        r = broker.execute(SQL)
        assert r.get("exceptions") == [], r
        assert r["resultTable"]["rows"][0] == [n_rows, total]


class TestShutdownDrain:
    def test_rejects_new_submits_while_draining(self, tmp_path):
        from pinot_tpu.engine.datatable import ServerShuttingDown, decode
        from pinot_tpu.transport.grpc_transport import make_instance_request

        registry = ClusterRegistry()
        server = ServerInstance("s0", registry, str(tmp_path / "sd"),
                                device_executor=None)
        server._shutting_down = True
        payload = make_instance_request("SELECT COUNT(*) FROM t", ["x"], 1,
                                        "b")
        with pytest.raises(ServerShuttingDown):
            decode(server._handle_submit(payload))

    def test_drain_waits_for_inflight_then_times_out(self, tmp_path):
        registry = ClusterRegistry()
        server = ServerInstance("s0", registry, str(tmp_path / "sd"),
                                device_executor=None)
        server.transport.start()
        server.registry.register_instance  # no sync loop started
        server._inflight_queries = 1  # simulate a stuck in-flight query
        t0 = time.perf_counter()
        server.stop(drain_timeout_s=0.3)
        elapsed = time.perf_counter() - t0
        assert 0.25 <= elapsed < 2.0  # waited the window, then proceeded

    def test_drain_window_configurable(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PINOT_TPU_PINOT_SERVER_SHUTDOWN_DRAIN_TIMEOUT_MS",
                           "1234")
        registry = ClusterRegistry()
        server = ServerInstance("s0", registry, str(tmp_path / "sd"),
                                device_executor=None)
        assert server.drain_timeout_s == pytest.approx(1.234)


# ---------------------------------------------------------------------------
# device-error recovery + quarantine breaker
# ---------------------------------------------------------------------------


ROWS = 4000


@pytest.fixture(scope="module")
def device_table(tmp_path_factory):
    rng = np.random.default_rng(7)
    schema = Schema.build(
        name="t",
        dimensions=[("tag", DataType.STRING)],
        metrics=[("m", DataType.INT), ("v", DataType.INT)],
    )
    cfg = TableConfig(table_name="t")
    base = tmp_path_factory.mktemp("faultseg")
    segs = []
    for i in range(2):
        cols = {
            "tag": np.array(["a", "b", "c"])[rng.integers(0, 3, ROWS)],
            "m": rng.integers(0, 1000, ROWS).astype(np.int32),
            "v": rng.integers(0, 1000, ROWS).astype(np.int32),
        }
        build_segment(schema, cols, str(base / f"s{i}"), cfg, f"s{i}")
        segs.append(ImmutableSegment(str(base / f"s{i}")))
    return segs


def _engines(segs):
    eng = QueryEngine()
    host = QueryEngine(device_executor=None)
    for s in segs:
        eng.add_segment("t", s)
        host.add_segment("t", s)
    return eng, host


class TestDeviceErrorRecovery:
    def test_launch_failure_retries_once_then_succeeds(self, device_table):
        eng, host = _engines(device_table)
        sql = "SELECT SUM(m) FROM t"
        expected = host.execute(sql)["resultTable"]["rows"]
        faults.install(faults.Fault(point="device.launch", mode="error",
                                    times=1))
        r = eng.execute(sql)
        assert not r.get("exceptions"), r
        assert r["resultTable"]["rows"] == expected
        assert eng.device.launch_failures >= 1
        # one failure is below the quarantine threshold
        assert eng.device.hbm_stats()["quarantined_pipelines"] == 0

    def test_fetch_failure_falls_back_to_host(self, device_table):
        eng, host = _engines(device_table)
        sql = "SELECT tag, COUNT(*), SUM(v) FROM t GROUP BY tag ORDER BY tag"
        expected = host.execute(sql)["resultTable"]["rows"]
        faults.install(faults.Fault(point="device.fetch", mode="error",
                                    times=1))
        before = eng.device.launch_failures
        r = eng.execute(sql)
        assert not r.get("exceptions"), r
        assert r["resultTable"]["rows"] == expected
        assert eng.device.launch_failures == before + 1

    def test_quarantine_routes_poisoned_template_to_host(self, device_table):
        eng, host = _engines(device_table)
        poisoned = "SELECT SUM(m) FROM t"
        # a different template over the same batch (metadata-only fast
        # paths don't count: it must actually LAUNCH on device)
        healthy_sql = "SELECT SUM(v) FROM t WHERE tag <> 'zz'"
        exp_p = host.execute(poisoned)["resultTable"]["rows"]
        exp_h = host.execute(healthy_sql)["resultTable"]["rows"]
        # unlimited failures for the sum(m) template ONLY
        faults.install(faults.Fault(point="device.launch", target="sum(m)",
                                    mode="error"))
        fault = faults.active_faults()[0]
        # launch + its retry both fail → quarantined → host answers
        r = eng.execute(poisoned)
        assert not r.get("exceptions"), r
        assert r["resultTable"]["rows"] == exp_p
        stats = eng.device.hbm_stats()
        assert stats["device_failures"] >= 2
        assert stats["quarantined_pipelines"] == 1
        fired_after_quarantine = fault.fired
        # quarantined: the breaker short-circuits BEFORE the injection
        # seam — no more device attempts for this template
        r = eng.execute(poisoned)
        assert r["resultTable"]["rows"] == exp_p
        assert fault.fired == fired_after_quarantine
        # a DIFFERENT template keeps running on device (the fault
        # doesn't match it, and the quarantine is per-template)
        leaves_before = eng.device.fetch_leaves_total
        r = eng.execute(healthy_sql)
        assert r["resultTable"]["rows"] == exp_h
        assert eng.device.fetch_leaves_total > leaves_before  # device path
        assert eng.device.hbm_stats()["quarantined_pipelines"] == 1
        # operational reset forgets the history
        eng.device.reset_quarantine()
        assert eng.device.hbm_stats()["quarantined_pipelines"] == 0


# ---------------------------------------------------------------------------
# chunklet-promotion failure (consuming segments stay correct on host tail)
# ---------------------------------------------------------------------------


class TestChunkletPromotionFault:
    def test_promotion_failure_keeps_ingest_and_queries_correct(self):
        from pinot_tpu.common.table_config import ChunkletConfig
        from pinot_tpu.storage.mutable import MutableSegment

        schema = Schema.build(
            name="rt",
            dimensions=[("zone", DataType.STRING)],
            metrics=[("fare", DataType.INT)],
            datetimes=[("ts", DataType.LONG)],
        )
        cfg = TableConfig(
            table_name="rt",
            chunklets=ChunkletConfig(enabled=True, rows_per_chunklet=1024,
                                     device_min_rows=0))
        rng = np.random.default_rng(3)
        rows = [{"zone": f"z{int(rng.integers(0, 20)):02d}",
                 "fare": int(rng.integers(0, 1000)), "ts": i}
                for i in range(3000)]

        faults.install(faults.Fault(point="chunklet.promote", mode="error"))
        seg = MutableSegment(schema, "rt__0", cfg)
        seg.index_batch(rows)
        try:
            seg.chunklet_index.promote()
            raise AssertionError("fault should have fired")
        except faults.FaultInjected:
            pass
        assert seg.n_docs == 3000
        assert len(seg.chunklet_index.chunklets) == 0  # nothing promoted

        eng = QueryEngine(device_executor=None)
        eng.table("rt").add_segment(seg)
        r = eng.execute("SELECT COUNT(*), SUM(fare) FROM rt")
        assert not r.get("exceptions"), r
        assert r["resultTable"]["rows"][0] == [
            3000, sum(x["fare"] for x in rows)]

        # fault cleared: the NEXT promotion catches up the frozen prefix
        # and answers stay identical
        faults.clear()
        assert seg.chunklet_index.promote() > 0
        r2 = eng.execute("SELECT COUNT(*), SUM(fare) FROM rt")
        assert r2["resultTable"]["rows"] == r["resultTable"]["rows"]

    def test_consume_helper_swallows_promotion_failure(self):
        # consume_stream_batches must treat a promote raise as non-fatal
        from pinot_tpu.realtime.chunklet import consume_stream_batches
        from pinot_tpu.common.table_config import ChunkletConfig
        from pinot_tpu.storage.mutable import MutableSegment

        schema = Schema.build(
            name="rt", dimensions=[("zone", DataType.STRING)],
            metrics=[("fare", DataType.INT)],
            datetimes=[("ts", DataType.LONG)])
        cfg = TableConfig(
            table_name="rt",
            chunklets=ChunkletConfig(enabled=True, rows_per_chunklet=512,
                                     device_min_rows=0))
        seg = MutableSegment(schema, "rt__0", cfg)

        class OneBatchConsumer:
            def __init__(self):
                self.offset = 0

            def fetch_payload_batch(self, start, max_rows):
                if start > 0:
                    return [], start
                import json as _json

                return [
                    _json.dumps({"zone": "z1", "fare": i, "ts": i}).encode()
                    for i in range(1024)
                ], 1024

        import json as _json

        faults.install(faults.Fault(point="chunklet.promote", mode="error"))
        indexed, next_off, fetched = consume_stream_batches(
            seg, OneBatchConsumer(), lambda p: _json.loads(p.decode()), 0)
        assert indexed == 1024 and next_off == 1024
        assert seg.n_docs == 1024  # rows survived the failed promotion


# ---------------------------------------------------------------------------
# scheduler.admit: admission starvation (ISSUE 14)
# ---------------------------------------------------------------------------


class TestAdmissionFaults:
    """The ``scheduler.admit`` injection point (modes error|delay) starves
    admission deterministically at BOTH seams — the broker's tenant
    admission controller (target = tenant) and the server's scheduler
    admission (target = instance id). Contract: typed 429 / degraded /
    QUERY_SCHEDULING_TIMEOUT responses, bounded latency, never a hang."""

    def test_broker_admission_fault_typed_429(self, cluster, tmp_path):
        registry, controller, servers, _b = cluster
        _push_table(tmp_path, controller, registry)
        from pinot_tpu.broker.admission import TenantAdmissionController

        broker = Broker(registry, timeout_s=10.0,
                        admission=TenantAdmissionController())
        try:
            faults.install(faults.Fault(point="scheduler.admit",
                                        target="tenantA", mode="error"))
            t0 = time.perf_counter()
            r = broker.execute("SET workloadName='tenantA'; " + SQL)
            took = time.perf_counter() - t0
            assert r["exceptions"][0]["errorCode"] == 429, r
            assert r["sheddingReason"] == "admission_fault"
            assert r["tenant"] == "tenantA"
            assert 0 < r["retryAfterSeconds"] <= 5
            assert took < 2.0, "admission fault must answer immediately"
            # an unmatched tenant is untouched by the armed fault
            rb = broker.execute("SET workloadName='tenantB'; " + SQL)
            assert not rb.get("exceptions"), rb
        finally:
            broker.close()

    def test_broker_admission_fault_degrades_to_stale(self, cluster,
                                                      tmp_path):
        """With ``maxStalenessMs`` allowed, a starved admission degrades
        to a flagged stale cache read instead of a 429 — chaos proves the
        brownout path end to end."""
        registry, controller, servers, _b = cluster
        total, n_rows = _push_table(tmp_path, controller, registry)
        from pinot_tpu.broker.admission import TenantAdmissionController

        broker = Broker(registry, timeout_s=10.0, result_cache=True,
                        admission=TenantAdmissionController())
        try:
            # warm the cache BEFORE arming chaos (the fresh path opts out
            # while faults are active; the shed path must still find it)
            warm = broker.execute("SET workloadName='tenantA'; " + SQL)
            assert not warm.get("exceptions"), warm
            faults.install(faults.Fault(point="scheduler.admit",
                                        target="tenantA", mode="error"))
            r = broker.execute("SET workloadName='tenantA'; "
                               "SET maxStalenessMs=60000; " + SQL)
            assert r.get("servedStale") is True, r
            assert r["sheddingReason"] == "admission_fault"
            assert r["resultTable"]["rows"][0] == [n_rows, total]
            assert 0 <= r["staleAgeMs"] <= 60000
        finally:
            broker.close()

    def test_server_admission_starved_typed_never_hangs(self, cluster,
                                                        tmp_path):
        """Every server's admission starved: the broker answers a typed
        in-band scheduling error (the server is healthy — no detector
        poisoning, no transport fault, no hang)."""
        registry, controller, servers, broker = cluster
        _push_table(tmp_path, controller, registry)
        faults.install(faults.Fault(point="scheduler.admit",
                                    target="server_", mode="error"))
        t0 = time.perf_counter()
        r = broker.execute(SQL)
        took = time.perf_counter() - t0
        excs = r.get("exceptions") or []
        assert excs, r
        assert "QUERY_SCHEDULING_TIMEOUT" in excs[0]["message"]
        assert took < 5.0, "starved admission must not hang"
        # the detector was not poisoned: the next (fault-free) query
        # routes and completes normally
        faults.clear()
        ok = broker.execute(SQL)
        assert not ok.get("exceptions"), ok

    def test_server_admission_delay_slows_but_succeeds(self, cluster,
                                                       tmp_path):
        registry, controller, servers, broker = cluster
        total, n_rows = _push_table(tmp_path, controller, registry)
        faults.install(faults.Fault(point="scheduler.admit",
                                    target="server_", mode="delay",
                                    delay_ms=300))
        t0 = time.perf_counter()
        r = broker.execute(SQL)
        took = time.perf_counter() - t0
        assert not r.get("exceptions"), r
        assert r["resultTable"]["rows"][0] == [n_rows, total]
        assert took >= 0.25, "the admission delay must actually bite"
