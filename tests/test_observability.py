"""Metrics registry, request tracing, FS SPI, plugin loader.

Reference analogs: AbstractMetrics + yammer reporters (histogram
percentiles included), Tracing.java / trace query option surfaced in
BrokerResponse (cross-process since ISSUE 7: trace id + per-server span
ladders merged into per-instance traceInfo, retries/hedges tagged),
PinotFS + LocalPinotFS, PluginManager + ServiceLoader-style
registration, and the broker QueryLogger (structured JSONL query log).
"""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.broker.http_api import BrokerHttpServer
from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.metrics import MetricsRegistry, get_metrics
from pinot_tpu.common.plugins import plugin_registry
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.fs import LocalFS, create_fs


def wait_until(cond, timeout=15.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


class TestMetricsRegistry:
    def test_counters_gauges_timers(self):
        reg = MetricsRegistry("test")
        reg.count("q")
        reg.count("q", 4)
        reg.gauge("depth", 7)
        reg.gauge("dynamic", lambda: 3)
        with reg.timed("phase"):
            pass
        reg.time_ms("phase", 5.0)
        snap = reg.snapshot()
        assert snap["counters"]["test.q"] == 5
        assert snap["gauges"]["test.depth"] == 7
        assert snap["gauges"]["test.dynamic"] == 3
        t = snap["timers"]["test.phase"]
        assert t["count"] == 2 and t["maxMs"] >= 5.0

    def test_tags_and_prometheus(self):
        reg = MetricsRegistry("b")
        reg.count("queries", tag="t1")
        reg.gauge("g", 1.5)
        reg.time_ms("lat", 10)
        text = reg.prometheus_text()
        assert "pinot_tpu_b_queries_t1_total 1" in text
        assert "pinot_tpu_b_g 1.5" in text
        assert "pinot_tpu_b_lat_ms_count 1" in text

    def test_reporter(self):
        reg = MetricsRegistry("r")
        seen = []
        reg.add_reporter(seen.append)
        reg.count("x")
        reg.report()
        assert seen and seen[0]["counters"]["r.x"] == 1

    def test_gauge_sampling_never_throws(self):
        reg = MetricsRegistry("g")
        reg.gauge("bad", lambda: 1 / 0)
        assert reg.snapshot()["gauges"]["g.bad"] is None


class TestFsSpi:
    def test_localfs_ops(self, tmp_path):
        fs = LocalFS()
        d = str(tmp_path / "a")
        fs.mkdir(d)
        assert fs.exists(d)
        with open(os.path.join(d, "f.txt"), "w") as f:
            f.write("hi")
        fs.copy(d, str(tmp_path / "b"))
        assert fs.list_files(str(tmp_path / "b")) == ["f.txt"]
        fs.copy(os.path.join(d, "f.txt"), str(tmp_path / "c" / "f.txt"))
        assert fs.exists(str(tmp_path / "c" / "f.txt"))
        fs.delete(d)
        assert not fs.exists(d)
        assert fs.exists("file://" + str(tmp_path / "b"))

    def test_create_fs_via_plugin_registry(self, tmp_path, monkeypatch):
        import sys

        assert isinstance(create_fs(str(tmp_path)), LocalFS)
        assert isinstance(create_fs("file:///x"), LocalFS)
        # s3 registers (pinot-s3 analog) but gates on boto3 — force-absent
        # so the assertion holds even on hosts that ship the SDK
        monkeypatch.setitem(sys.modules, "boto3", None)
        with pytest.raises(RuntimeError, match="boto3"):
            create_fs("s3://bucket/x")
        monkeypatch.setitem(sys.modules, "google", None)
        monkeypatch.setitem(sys.modules, "google.cloud", None)
        with pytest.raises(RuntimeError, match="google-cloud"):
            create_fs("gs://bucket/x")
        # hdfs is now a real plugin (WebHDFS, stdlib-only — no gating)
        from pinot_tpu.storage.hdfsfs import HdfsFS

        assert isinstance(create_fs("hdfs://nn:9870/x"), HdfsFS)
        with pytest.raises(KeyError, match="no 'fs' plugin"):
            create_fs("ipfs://nn/x")


class TestPluginRegistry:
    def test_builtins_registered(self):
        assert "memory" in plugin_registry.available("stream")
        assert "json" in plugin_registry.available("decoder")
        assert {"csv", "json", "parquet"} <= set(
            plugin_registry.available("record_reader"))
        assert "mergerolluptask" in plugin_registry.available("minion_task")
        assert plugin_registry.load("fs", "file") is LocalFS

    def test_unknown_plugin_raises_with_inventory(self):
        with pytest.raises(KeyError, match="registered"):
            plugin_registry.load("stream", "kafka")

    def test_env_plugin_module_loads(self, tmp_path, monkeypatch):
        mod_dir = tmp_path / "plugmod"
        mod_dir.mkdir()
        (mod_dir / "my_plugin.py").write_text(
            "from pinot_tpu.common.plugins import plugin_registry\n"
            "plugin_registry.register('decoder', 'upper', lambda b: b.upper())\n"
        )
        monkeypatch.syspath_prepend(str(mod_dir))
        monkeypatch.setenv("PINOT_TPU_PLUGINS", "my_plugin")
        # plugin modules register on the GLOBAL registry at import
        assert plugin_registry.load_env_plugins()
        assert plugin_registry.load("decoder", "upper")(b"x") == b"X"


class TestClusterObservability:
    @pytest.fixture()
    def cluster(self, tmp_path):
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        server = ServerInstance("server_0", registry, str(tmp_path / "s0"),
                                device_executor=None)
        server.start()
        broker = Broker(registry, timeout_s=10.0)
        http = BrokerHttpServer(broker)
        http.start()
        schema = Schema.build(
            name="sales",
            dimensions=[("k", DataType.STRING)],
            metrics=[("v", DataType.LONG)],
        )
        cfg = TableConfig(table_name="sales")
        controller.add_table(cfg, schema)
        d = str(tmp_path / "up")
        build_segment(
            schema,
            {"k": np.array(["a", "b"] * 50), "v": np.arange(100, dtype=np.int64)},
            d, cfg, "s0")
        controller.upload_segment("sales", d)
        assert wait_until(lambda: len(registry.external_view("sales_OFFLINE")) == 1)
        yield broker, http
        http.stop()
        broker.close()
        server.stop()

    def test_trace_option_returns_phase_spans(self, cluster):
        broker, _ = cluster
        r = broker.execute(
            "SET trace = true; SELECT k, SUM(v) FROM sales GROUP BY k")
        assert not r.get("exceptions"), r
        info = r["traceInfo"]
        assert "broker" in info and "server_0" in info
        broker_phases = {s["phase"] for s in info["broker"]}
        assert {"broker.scatter_gather", "broker.reduce"} <= broker_phases
        server_phases = {s["phase"] for s in info["server_0"]}
        assert "server.execute" in server_phases
        assert all(s["durationMs"] >= 0 for s in info["server_0"])
        # tracing off → no traceInfo
        r2 = broker.execute("SELECT COUNT(*) FROM sales")
        assert "traceInfo" not in r2

    def test_metrics_http_endpoints(self, cluster):
        broker, http = cluster
        broker.execute("SELECT COUNT(*) FROM sales")
        with urllib.request.urlopen(http.url + "/metrics", timeout=5) as resp:
            snap = json.loads(resp.read())
        assert snap["broker"]["counters"]["broker.queries"] >= 1
        assert snap["server"]["counters"]["server.queries"] >= 1
        assert snap["server"]["timers"]["server.query"]["count"] >= 1
        gauges = snap["server"]["gauges"]
        assert gauges["server.segmentsLoaded.server_0"] >= 1
        with urllib.request.urlopen(http.url + "/metrics/prometheus",
                                    timeout=5) as resp:
            text = resp.read().decode()
        assert "pinot_tpu_broker_queries_total" in text

    def test_parse_error_counts_query_before_error(self, tmp_path):
        """The server counts ``queries`` at RECEIVE time (pre-compile), so
        a stream of parse errors can never push queryErrors above queries
        on the dashboard (the old inner-count, incremented only after a
        successful compile + admission, made the invariant violable)."""
        from pinot_tpu.common.metrics import get_metrics
        from pinot_tpu.transport.grpc_transport import make_instance_request

        registry = ClusterRegistry()
        server = ServerInstance("server_m", registry, str(tmp_path / "sm"),
                                device_executor=None)
        m = get_metrics("server")
        snap0 = m.snapshot()["counters"]
        q0 = snap0.get("server.queries", 0)
        e0 = snap0.get("server.queryErrors", 0)
        bad = make_instance_request("SELEKT garbage FRM nowhere", [], 1, "b0")
        resp = server._handle_submit(bad)
        assert b"query_error" in resp
        snap = m.snapshot()["counters"]
        assert snap.get("server.queryErrors", 0) == e0 + 1
        assert snap.get("server.queries", 0) == q0 + 1


# ---------------------------------------------------------------------------
# ISSUE 7: histogram metrics
# ---------------------------------------------------------------------------


class TestHistogram:
    def test_quantiles_vs_numpy_across_bucket_boundaries(self):
        """Log-bucket interpolation must track exact percentiles within
        one bucket width (~19% worst case; far tighter in practice)
        across distributions that straddle many bucket boundaries."""
        from pinot_tpu.common.metrics import Histogram

        rng = np.random.default_rng(7)
        for dist in (
            rng.uniform(0.5, 200.0, 4000),          # flat across buckets
            rng.lognormal(2.0, 1.5, 4000),          # heavy tail
            np.arange(1, 301, dtype=np.float64),    # exact ladder
            np.repeat([0.9, 1.1, 99.0, 101.0], 50), # boundary-straddling
        ):
            h = Histogram()
            for v in dist:
                h.update(float(v))
            s = np.sort(dist)
            for q in (0.5, 0.9, 0.99):
                # nearest-rank oracle (the histogram's own definition —
                # numpy's default interpolates ACROSS distribution gaps,
                # which no bucketed histogram can reproduce)
                exact = float(s[max(0, int(np.ceil(q * len(s))) - 1)])
                est = h.quantile(q)
                assert abs(est - exact) <= max(0.20 * exact, 1e-3), \
                    (q, est, exact)

    def test_quantiles_clamped_to_observed_range(self):
        from pinot_tpu.common.metrics import Histogram

        h = Histogram()
        for v in (5.0, 5.0, 5.0):
            h.update(v)
        assert h.quantile(0.5) == 5.0
        assert h.quantile(0.999) == 5.0
        snap = h.snapshot()
        assert snap["count"] == 3 and snap["p99Ms"] == 5.0

    def test_registry_one_update_feeds_timer_and_histogram(self):
        from pinot_tpu.common.metrics import MetricsRegistry

        reg = MetricsRegistry("h")
        for v in range(1, 101):
            reg.time_ms("lat", float(v))
        snap = reg.snapshot()
        assert snap["timers"]["h.lat"]["count"] == 100
        hist = snap["histograms"]["h.lat"]
        assert hist["count"] == 100
        assert 40 <= hist["p50Ms"] <= 60
        assert 85 <= hist["p90Ms"] <= 100
        # quantile() is the shared-read surface (hedge delay et al.)
        assert reg.quantile("lat", 0.9) == pytest.approx(
            hist["p90Ms"], abs=1e-3)  # snapshot rounds to 3 decimals
        assert reg.quantile("nothing", 0.9) is None
        # observe() is the histogram-forward alias of time_ms
        reg.observe("lat2", 5.0)
        assert reg.snapshot()["histograms"]["h.lat2"]["count"] == 1

    def test_prometheus_histogram_exposition_parses(self):
        """The exposition must hold up under prometheus_client's
        text-format parser: histogram family with monotone cumulative
        buckets, +Inf, _sum/_count consistency."""
        from pinot_tpu.common.metrics import MetricsRegistry

        prom_parser = pytest.importorskip("prometheus_client.parser")
        reg = MetricsRegistry("p")
        reg.count("queries")
        reg.gauge("depth", 3)
        for v in (0.5, 5.0, 50.0, 500.0, 5000.0):
            reg.time_ms("query", v)
        text = reg.prometheus_text()
        fams = {f.name: f for f in
                prom_parser.text_string_to_metric_families(text)}
        assert fams["pinot_tpu_p_queries"].type == "counter"
        hist = fams["pinot_tpu_p_query_ms"]
        assert hist.type == "histogram"
        buckets = [(s.labels["le"], s.value) for s in hist.samples
                   if s.name.endswith("_bucket")]
        assert buckets[-1][0] == "+Inf" and buckets[-1][1] == 5
        values = [v for _le, v in buckets]
        assert values == sorted(values), "cumulative buckets must be monotone"
        count = next(s.value for s in hist.samples
                     if s.name.endswith("_count"))
        total = next(s.value for s in hist.samples
                     if s.name.endswith("_sum"))
        assert count == 5 and total == pytest.approx(5555.5)


class TestMetricsLifecycle:
    def test_reset_clears_registry(self):
        from pinot_tpu.common.metrics import MetricsRegistry

        reg = MetricsRegistry("x")
        reg.count("a")
        reg.gauge("g", lambda: 1)
        reg.time_ms("t", 1.0)
        reg.reset()
        snap = reg.snapshot()
        assert not snap["counters"] and not snap["gauges"]
        assert not snap["timers"] and not snap["histograms"]

    def test_reset_metrics_by_component(self):
        from pinot_tpu.common.metrics import get_metrics, reset_metrics

        get_metrics("resettest").count("a")
        reset_metrics("resettest")
        assert not get_metrics("resettest").snapshot()["counters"]
        get_metrics("resettest").count("a")
        reset_metrics()  # all registries
        assert not get_metrics("resettest").snapshot()["counters"]

    def test_server_stop_unregisters_every_gauge(self, tmp_path):
        """Leak guard (ISSUE 7 satellite): get_metrics registries are
        process-global and survive ServerInstance.stop() — every
        callable gauge the instance registered (segments, scheduler,
        device HBM/quarantine family) must unregister on stop, or the
        closure pins the dead instance and a restarted same-id server
        double-reports."""
        from pinot_tpu.cluster.registry import ClusterRegistry
        from pinot_tpu.common.metrics import get_metrics
        from pinot_tpu.server.server import ServerInstance

        m = get_metrics("server")
        for round_i in range(2):  # restart with the SAME instance id
            server = ServerInstance(
                "leakguard_0", ClusterRegistry(),
                str(tmp_path / f"lg{round_i}"))
            server.start()
            keys = m.gauge_keys("leakguard_0")
            assert "server.segmentsLoaded.leakguard_0" in keys
            # the device gauge family (PR-5/PR-6) registers too
            assert any("deviceResidentBytes" in k for k in keys)
            assert any("deviceQuarantinedPipelines" in k for k in keys)
            # ISSUE 11: the roofline + temperature gauges join the
            # tracked family — a restart must not leak them either
            assert any("heatTrackedSegments" in k for k in keys)
            assert any("hbmPeakGbps" in k for k in keys)
            server.stop(drain_timeout_s=0.2)
            assert m.gauge_keys("leakguard_0") == [], \
                "stop() leaked callable gauges into the global registry"


# ---------------------------------------------------------------------------
# ISSUE 7: explicit tracer across the async launch/fetch split + cohorts
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def traced_engine(tmp_path_factory):
    """Small two-segment device-eligible table for tracer plumbing."""
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.engine.engine import QueryEngine
    from pinot_tpu.storage.creator import build_segment
    from pinot_tpu.storage.segment import ImmutableSegment

    base = tmp_path_factory.mktemp("traced")
    schema = Schema.build(
        name="t",
        dimensions=[("tag", DataType.STRING)],
        metrics=[("v", DataType.INT)],
    )
    cfg = TableConfig(table_name="t")
    rng = np.random.default_rng(3)
    segs = []
    for i in range(2):
        cols = {
            "tag": np.array(["a", "b", "c"])[rng.integers(0, 3, 20_000)],
            "v": rng.integers(0, 100, 20_000).astype(np.int32),
        }
        d = str(base / f"s{i}")
        build_segment(schema, cols, d, cfg, f"s{i}")
        segs.append(ImmutableSegment(d))
    eng = QueryEngine()
    for s in segs:
        eng.add_segment("t", s)
    return eng, segs


class TestTracerAcrossAsyncSplit:
    def _compile(self, sql):
        from pinot_tpu.query.optimizer import optimize_query
        from pinot_tpu.sql.compiler import compile_query

        return optimize_query(compile_query(sql))

    def test_async_query_reports_launch_and_fetch_spans(self, traced_engine):
        """Regression for the PR-2 thread-split span loss: the tracer is
        carried EXPLICITLY through execute_segments_async and the device
        handle, so a traced async query reports both launch-phase spans
        (gather/dispatch) and fetch-phase spans (device_fetch, merge) —
        even when fetch() runs on a different thread than launch."""
        from pinot_tpu.common.trace import Tracer

        eng, _segs = traced_engine
        q = self._compile("SELECT tag, SUM(v) FROM t GROUP BY tag")
        tracer = Tracer("test-trace-1")
        tdm = eng.tables["t"]
        segs = tdm.acquire()
        try:
            fetch = eng.execute_segments_async(q, segs, tracer=tracer)
            result_box = []
            th = threading.Thread(  # the deferred fetch on ANOTHER thread
                target=lambda: result_box.append(fetch()))
            th.start()
            th.join(60)
        finally:
            tdm.release(segs)
        assert result_box, "fetch thread died"
        phases = {s["phase"] for s in tracer.to_json()}
        assert "gather" in phases, phases          # launch: column gather
        assert "dispatch" in phases, phases        # launch: XLA dispatch
        assert "device_fetch" in phases, phases    # fetch: link wait
        assert "merge" in phases, phases           # fetch: partial merge
        # kernel/link split recorded under the fetch wait
        assert any(p.endswith("kernel") for p in phases), phases
        assert any(p.endswith("link") for p in phases), phases

    def test_cohort_members_each_get_fetch_spans(self, traced_engine):
        """Coalesced cohort launches: every MEMBER's tracer records its
        own fetch-phase span (the shared kernel/link spans land on the
        leader's trace) — previously cohort spans landed on whichever
        thread's thread-local happened to be installed, or nowhere."""
        from pinot_tpu.common.trace import Tracer

        eng, _segs = traced_engine
        dev = eng.device
        dev.partials_cache_enabled = False  # pin cohorts, not cache hits
        co = dev.coalescer
        co.force = True
        co.window_s = 0.25
        n = 3
        tracers = [Tracer(f"cohort-{i}") for i in range(n)]
        results = [None] * n
        errors = []
        barrier = threading.Barrier(n)
        c0 = co.queries_coalesced
        tdm = eng.tables["t"]

        def worker(i):
            q = self._compile(
                f"SELECT tag, SUM(v) FROM t WHERE v < {90 + i} GROUP BY tag")
            segs = tdm.acquire()
            try:
                barrier.wait(10)
                fetch = eng.execute_segments_async(q, segs,
                                                   tracer=tracers[i])
                results[i] = fetch()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                tdm.release(segs)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
        finally:
            co.force = False
            co.window_s = 0.003
        assert not errors, errors
        assert all(r is not None for r in results)
        assert co.queries_coalesced > c0, "queries never coalesced"
        for i, tr in enumerate(tracers):
            phases = {s["phase"] for s in tr.to_json()}
            assert "gather" in phases, (i, phases)
            assert "device_fetch" in phases, (i, phases)


# ---------------------------------------------------------------------------
# ISSUE 7: traceInfo merge under retry/hedge + structured query log
# ---------------------------------------------------------------------------


@pytest.fixture()
def replicated_cluster(tmp_path):
    """2 servers x replication 2 (every segment on both) — the retry and
    hedge paths always have a covering replica."""
    from pinot_tpu.common import faults

    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "ds"))
    servers = [
        ServerInstance(f"rsrv_{i}", registry, str(tmp_path / f"r{i}"),
                       device_executor=None)
        for i in range(2)
    ]
    for s in servers:
        s.start()
    schema = Schema.build(
        name="rt",
        dimensions=[("k", DataType.STRING)],
        metrics=[("v", DataType.LONG)],
    )
    cfg = TableConfig(table_name="rt", replication=2)
    controller.add_table(cfg, schema)
    rng = np.random.default_rng(1)
    for i in range(2):
        d = str(tmp_path / f"up{i}")
        build_segment(
            schema,
            {"k": np.array(["a", "b", "c"])[rng.integers(0, 3, 3000)],
             "v": rng.integers(0, 50, 3000).astype(np.int64)},
            d, cfg, f"rt_s{i}")
        controller.upload_segment("rt", d)
    ev_ok = wait_until(lambda: (
        len(registry.external_view("rt_OFFLINE")) == 2
        and all(len(v) == 2
                for v in registry.external_view("rt_OFFLINE").values())))
    assert ev_ok, "segments never fully replicated"
    yield registry, servers
    faults.clear()
    for s in servers:
        try:
            s.stop(drain_timeout_s=0.2)
        except Exception:  # noqa: BLE001
            pass


TRACED_SQL = "SET trace = true; SELECT k, SUM(v) FROM rt GROUP BY k ORDER BY k"


class TestTraceMergeRetryHedge:
    def _server_keys(self, info):
        return {k for k in info if k != "broker"}

    def test_retry_attempt_traces_tagged_and_merged(self, replicated_cluster):
        """A replica that hard-fails forces a retry; the retry attempt's
        server spans must arrive in traceInfo TAGGED as a retry, with no
        duplicate and no dropped span lists, and the recovered result
        must be complete (no partialResult)."""
        from pinot_tpu.common import faults

        registry, _servers = replicated_cluster
        reference = None
        broker = Broker(registry, timeout_s=10.0)
        try:
            reference = broker.execute(
                "SELECT k, SUM(v) FROM rt GROUP BY k ORDER BY k")
            assert not reference.get("exceptions")
        finally:
            broker.close()

        faults.install(faults.Fault(
            point="transport.submit", target="rsrv_0", mode="error"))
        broker = Broker(registry, timeout_s=10.0)
        try:
            saw_retry = False
            for _ in range(3):  # round-robin: one of these routes rsrv_0
                r = broker.execute(TRACED_SQL)
                assert not r.get("exceptions"), r
                assert not r.get("partialResult")
                assert r["resultTable"]["rows"] == \
                    reference["resultTable"]["rows"]
                info = r["traceInfo"]
                keys = self._server_keys(info)
                assert keys, "no server spans at all"
                for k in keys:
                    spans = info[k]
                    assert spans, f"empty span list under {k!r}"
                    # merged-by-extend, not overwritten: exactly one
                    # server.total per answering attempt part
                    totals = [s for s in spans
                              if s["phase"] == "server.total"]
                    assert len(totals) >= 1
                    assert all(s["durationMs"] >= 0 for s in spans)
                if any("(retry)" in k for k in keys):
                    saw_retry = True
                    assert r.get("numRetries", 0) >= 1
                    # the failed primary contributed NO span list of its
                    # own (its RPC died before the server ran)
                    assert not any(k.startswith("rsrv_0")
                                   and "(retry)" not in k for k in keys)
            assert saw_retry, "no query exercised the retry path"
        finally:
            faults.clear()
            broker.close()

    def test_hedge_attempt_traces_tagged(self, replicated_cluster):
        """A slow replica triggers a hedge; the winning hedge attempt's
        spans arrive tagged '(hedge)' and the response counts it."""
        from pinot_tpu.common import faults

        registry, _servers = replicated_cluster
        faults.install(faults.Fault(
            point="transport.submit", target="rsrv_0", mode="delay",
            delay_ms=400))
        broker = Broker(registry, timeout_s=10.0)
        broker.hedging_enabled = True
        broker.hedge_delay_s = 0.02
        try:
            saw_hedge = False
            for _ in range(3):
                r = broker.execute(TRACED_SQL)
                assert not r.get("exceptions"), r
                keys = self._server_keys(r["traceInfo"])
                if any("(hedge)" in k for k in keys):
                    saw_hedge = True
                    assert r.get("numHedges", 0) >= 1
            assert saw_hedge, "no query exercised the hedge path"
        finally:
            faults.clear()
            broker.close()

    def test_hedge_delay_driven_by_shared_histogram(self):
        """The acceptance wire: LatencyTracker.p90_s reads the SHARED
        metrics histogram — a recorded latency profile shows up both in
        the hedge delay and in the registry's histogram snapshot."""
        from pinot_tpu.broker.broker import LatencyTracker

        reg = MetricsRegistry("hb")
        lt = LatencyTracker(default_s=0.07, registry=reg)
        assert lt.p90_s("sX") == 0.07  # no samples: default
        for v in range(100):
            lt.record("sX", v / 1000.0)  # 0..99 ms
        p90 = lt.p90_s("sX")
        assert 0.075 <= p90 <= 0.11, p90
        hist = reg.snapshot()["histograms"]["hb.serverLatencyMs.sX"]
        assert hist["count"] == 100
        assert abs(hist["p90Ms"] / 1e3 - p90) < 1e-6


class TestQueryLog:
    def _resp(self, used_ms, exceptions=(), partial=False):
        return {"timeUsedMs": used_ms, "exceptions": list(exceptions),
                "partialResult": partial, "requestId": 1}

    def test_policy_always_on_for_abnormal(self, tmp_path):
        from pinot_tpu.broker.querylog import QueryLogger

        ql = QueryLogger(slow_threshold_ms=500.0, sample_rate=0.0)
        # fast + healthy: dropped
        assert ql.record("SELECT 1", self._resp(3.0), 3.0) is None
        # slow: kept
        assert ql.record("SELECT 2", self._resp(900.0), 900.0) is not None
        # fast but errored: kept
        assert ql.record(
            "SELECT 3",
            self._resp(3.0, [{"errorCode": 250, "message": "t"}]),
            3.0) is not None
        # fast but partial: kept
        assert ql.record(
            "SELECT 4", self._resp(3.0, partial=True), 3.0) is not None
        entries = ql.recent()
        assert len(entries) == 3
        assert entries[0]["sql"] == "SELECT 4"  # newest first

    def test_jsonl_write_and_rotation(self, tmp_path):
        from pinot_tpu.broker.querylog import QueryLogger

        path = str(tmp_path / "q.jsonl")
        ql = QueryLogger(path=path, slow_threshold_ms=0.0, max_bytes=2000)
        for i in range(40):
            ql.record(f"SELECT {i}", self._resp(10.0 + i), 10.0 + i)
        assert os.path.exists(path)
        assert os.path.exists(path + ".1"), "rotation never triggered"
        assert os.path.getsize(path) <= 2000 + 1024
        with open(path) as f:
            lines = [json.loads(line) for line in f if line.strip()]
        assert lines and all("timeUsedMs" in e for e in lines)

    def test_broker_logs_slow_query_with_trace_and_template(
            self, replicated_cluster, tmp_path):
        from pinot_tpu.broker.querylog import QueryLogger

        registry, _servers = replicated_cluster
        broker = Broker(registry, timeout_s=10.0)
        path = str(tmp_path / "bq.jsonl")
        # threshold 0: every query is "slow" — deterministic capture
        broker.querylog = QueryLogger(path=path, slow_threshold_ms=0.0)
        try:
            r = broker.execute(TRACED_SQL)
            assert not r.get("exceptions"), r
            entries = broker.querylog.recent()
            assert entries
            e = entries[0]
            assert e["table"] == "rt"
            assert e["traceId"] == r["traceId"]
            assert e["template"].startswith("rt|group_by|sum|k")
            assert "traceInfo" in e
            assert e["counters"]["numServersQueried"] >= 1
            # error queries log too, with their exception in place
            broker.execute("SELECT nope(v) FROM rt")
            bad = broker.querylog.recent()[0]
            assert bad["exceptions"]
        finally:
            broker.close()

    def test_debug_queries_endpoint(self, replicated_cluster, tmp_path):
        from pinot_tpu.broker.querylog import QueryLogger

        registry, _servers = replicated_cluster
        broker = Broker(registry, timeout_s=10.0)
        broker.querylog = QueryLogger(slow_threshold_ms=0.0, ring_size=8)
        http = BrokerHttpServer(broker)
        http.start()
        try:
            for _ in range(3):
                broker.execute("SELECT COUNT(*) FROM rt")
            with urllib.request.urlopen(
                    http.url + "/debug/queries?limit=2", timeout=5) as resp:
                doc = json.loads(resp.read())
            assert len(doc["queries"]) == 2
            assert all("timeUsedMs" in e for e in doc["queries"])
        finally:
            http.stop()
            broker.close()

    def test_summarizer_tool(self, tmp_path, capsys):
        from pinot_tpu.broker.querylog import QueryLogger
        from pinot_tpu.tools import querylog as qtool

        path = str(tmp_path / "sum.jsonl")
        ql = QueryLogger(path=path, slow_threshold_ms=0.0)
        for i in range(10):
            resp = self._resp(10.0 * (i + 1))
            resp["traceInfo"] = {"s0": [
                {"phase": "server.queue", "startMs": 0, "durationMs": 0.1},
                {"phase": "server.fetch.kernel", "startMs": 1,
                 "durationMs": 5.0},
                {"phase": "server.fetch.link", "startMs": 6,
                 "durationMs": 2.0},
            ]}
            ql.record(f"SELECT {i} FROM t", resp, 10.0 * (i + 1), table="t")
        rc = qtool.main([path, "--top", "2", "--json"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["queries"] == 10
        assert out["latencyMs"]["p50"] > 0
        assert out["phaseP50Ms"]["kernel"] == 5.0
        assert out["phaseP50Ms"]["link"] == 2.0
        assert len(out["slowest"]) == 2
