"""Metrics registry, request tracing, FS SPI, plugin loader.

Reference analogs: AbstractMetrics + yammer reporters, Tracing.java /
trace query option surfaced in BrokerResponse, PinotFS + LocalPinotFS,
PluginManager + ServiceLoader-style registration.
"""

import json
import os
import sys
import time
import urllib.request

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.broker.http_api import BrokerHttpServer
from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.metrics import MetricsRegistry, get_metrics
from pinot_tpu.common.plugins import plugin_registry
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.fs import LocalFS, create_fs


def wait_until(cond, timeout=15.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


class TestMetricsRegistry:
    def test_counters_gauges_timers(self):
        reg = MetricsRegistry("test")
        reg.count("q")
        reg.count("q", 4)
        reg.gauge("depth", 7)
        reg.gauge("dynamic", lambda: 3)
        with reg.timed("phase"):
            pass
        reg.time_ms("phase", 5.0)
        snap = reg.snapshot()
        assert snap["counters"]["test.q"] == 5
        assert snap["gauges"]["test.depth"] == 7
        assert snap["gauges"]["test.dynamic"] == 3
        t = snap["timers"]["test.phase"]
        assert t["count"] == 2 and t["maxMs"] >= 5.0

    def test_tags_and_prometheus(self):
        reg = MetricsRegistry("b")
        reg.count("queries", tag="t1")
        reg.gauge("g", 1.5)
        reg.time_ms("lat", 10)
        text = reg.prometheus_text()
        assert "pinot_tpu_b_queries_t1_total 1" in text
        assert "pinot_tpu_b_g 1.5" in text
        assert "pinot_tpu_b_lat_ms_count 1" in text

    def test_reporter(self):
        reg = MetricsRegistry("r")
        seen = []
        reg.add_reporter(seen.append)
        reg.count("x")
        reg.report()
        assert seen and seen[0]["counters"]["r.x"] == 1

    def test_gauge_sampling_never_throws(self):
        reg = MetricsRegistry("g")
        reg.gauge("bad", lambda: 1 / 0)
        assert reg.snapshot()["gauges"]["g.bad"] is None


class TestFsSpi:
    def test_localfs_ops(self, tmp_path):
        fs = LocalFS()
        d = str(tmp_path / "a")
        fs.mkdir(d)
        assert fs.exists(d)
        with open(os.path.join(d, "f.txt"), "w") as f:
            f.write("hi")
        fs.copy(d, str(tmp_path / "b"))
        assert fs.list_files(str(tmp_path / "b")) == ["f.txt"]
        fs.copy(os.path.join(d, "f.txt"), str(tmp_path / "c" / "f.txt"))
        assert fs.exists(str(tmp_path / "c" / "f.txt"))
        fs.delete(d)
        assert not fs.exists(d)
        assert fs.exists("file://" + str(tmp_path / "b"))

    def test_create_fs_via_plugin_registry(self, tmp_path, monkeypatch):
        import sys

        assert isinstance(create_fs(str(tmp_path)), LocalFS)
        assert isinstance(create_fs("file:///x"), LocalFS)
        # s3 registers (pinot-s3 analog) but gates on boto3 — force-absent
        # so the assertion holds even on hosts that ship the SDK
        monkeypatch.setitem(sys.modules, "boto3", None)
        with pytest.raises(RuntimeError, match="boto3"):
            create_fs("s3://bucket/x")
        monkeypatch.setitem(sys.modules, "google", None)
        monkeypatch.setitem(sys.modules, "google.cloud", None)
        with pytest.raises(RuntimeError, match="google-cloud"):
            create_fs("gs://bucket/x")
        # hdfs is now a real plugin (WebHDFS, stdlib-only — no gating)
        from pinot_tpu.storage.hdfsfs import HdfsFS

        assert isinstance(create_fs("hdfs://nn:9870/x"), HdfsFS)
        with pytest.raises(KeyError, match="no 'fs' plugin"):
            create_fs("ipfs://nn/x")


class TestPluginRegistry:
    def test_builtins_registered(self):
        assert "memory" in plugin_registry.available("stream")
        assert "json" in plugin_registry.available("decoder")
        assert {"csv", "json", "parquet"} <= set(
            plugin_registry.available("record_reader"))
        assert "mergerolluptask" in plugin_registry.available("minion_task")
        assert plugin_registry.load("fs", "file") is LocalFS

    def test_unknown_plugin_raises_with_inventory(self):
        with pytest.raises(KeyError, match="registered"):
            plugin_registry.load("stream", "kafka")

    def test_env_plugin_module_loads(self, tmp_path, monkeypatch):
        mod_dir = tmp_path / "plugmod"
        mod_dir.mkdir()
        (mod_dir / "my_plugin.py").write_text(
            "from pinot_tpu.common.plugins import plugin_registry\n"
            "plugin_registry.register('decoder', 'upper', lambda b: b.upper())\n"
        )
        monkeypatch.syspath_prepend(str(mod_dir))
        monkeypatch.setenv("PINOT_TPU_PLUGINS", "my_plugin")
        # plugin modules register on the GLOBAL registry at import
        assert plugin_registry.load_env_plugins()
        assert plugin_registry.load("decoder", "upper")(b"x") == b"X"


class TestClusterObservability:
    @pytest.fixture()
    def cluster(self, tmp_path):
        registry = ClusterRegistry()
        controller = Controller(registry, str(tmp_path / "ds"))
        server = ServerInstance("server_0", registry, str(tmp_path / "s0"),
                                device_executor=None)
        server.start()
        broker = Broker(registry, timeout_s=10.0)
        http = BrokerHttpServer(broker)
        http.start()
        schema = Schema.build(
            name="sales",
            dimensions=[("k", DataType.STRING)],
            metrics=[("v", DataType.LONG)],
        )
        cfg = TableConfig(table_name="sales")
        controller.add_table(cfg, schema)
        d = str(tmp_path / "up")
        build_segment(
            schema,
            {"k": np.array(["a", "b"] * 50), "v": np.arange(100, dtype=np.int64)},
            d, cfg, "s0")
        controller.upload_segment("sales", d)
        assert wait_until(lambda: len(registry.external_view("sales_OFFLINE")) == 1)
        yield broker, http
        http.stop()
        broker.close()
        server.stop()

    def test_trace_option_returns_phase_spans(self, cluster):
        broker, _ = cluster
        r = broker.execute(
            "SET trace = true; SELECT k, SUM(v) FROM sales GROUP BY k")
        assert not r.get("exceptions"), r
        info = r["traceInfo"]
        assert "broker" in info and "server_0" in info
        broker_phases = {s["phase"] for s in info["broker"]}
        assert {"broker.scatter_gather", "broker.reduce"} <= broker_phases
        server_phases = {s["phase"] for s in info["server_0"]}
        assert "server.execute" in server_phases
        assert all(s["durationMs"] >= 0 for s in info["server_0"])
        # tracing off → no traceInfo
        r2 = broker.execute("SELECT COUNT(*) FROM sales")
        assert "traceInfo" not in r2

    def test_metrics_http_endpoints(self, cluster):
        broker, http = cluster
        broker.execute("SELECT COUNT(*) FROM sales")
        with urllib.request.urlopen(http.url + "/metrics", timeout=5) as resp:
            snap = json.loads(resp.read())
        assert snap["broker"]["counters"]["broker.queries"] >= 1
        assert snap["server"]["counters"]["server.queries"] >= 1
        assert snap["server"]["timers"]["server.query"]["count"] >= 1
        gauges = snap["server"]["gauges"]
        assert gauges["server.segmentsLoaded.server_0"] >= 1
        with urllib.request.urlopen(http.url + "/metrics/prometheus",
                                    timeout=5) as resp:
            text = resp.read().decode()
        assert "pinot_tpu_broker_queries_total" in text

    def test_parse_error_counts_query_before_error(self, tmp_path):
        """The server counts ``queries`` at RECEIVE time (pre-compile), so
        a stream of parse errors can never push queryErrors above queries
        on the dashboard (the old inner-count, incremented only after a
        successful compile + admission, made the invariant violable)."""
        from pinot_tpu.common.metrics import get_metrics
        from pinot_tpu.transport.grpc_transport import make_instance_request

        registry = ClusterRegistry()
        server = ServerInstance("server_m", registry, str(tmp_path / "sm"),
                                device_executor=None)
        m = get_metrics("server")
        snap0 = m.snapshot()["counters"]
        q0 = snap0.get("server.queries", 0)
        e0 = snap0.get("server.queryErrors", 0)
        bad = make_instance_request("SELEKT garbage FRM nowhere", [], 1, "b0")
        resp = server._handle_submit(bad)
        assert b"query_error" in resp
        snap = m.snapshot()["counters"]
        assert snap.get("server.queryErrors", 0) == e0 + 1
        assert snap.get("server.queries", 0) == q0 + 1
