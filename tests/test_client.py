"""Python client (DB-API flavored) against broker HTTP and embedded.

Reference analogs: pinot-java-client Connection/ResultSetGroup, the
external pinotdb DB-API driver.
"""

import time

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.broker.http_api import BrokerHttpServer
from pinot_tpu.client import Connection, DatabaseError, ProgrammingError, connect
from pinot_tpu.cluster.registry import ClusterRegistry
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment


def wait_until(cond, timeout=15.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("client")
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp / "ds"))
    server = ServerInstance("server_0", registry, str(tmp / "s0"),
                            device_executor=None)
    server.start()
    broker = Broker(registry, timeout_s=10.0)
    http = BrokerHttpServer(broker)
    http.start()
    schema = Schema.build(
        name="cities",
        dimensions=[("name", DataType.STRING)],
        metrics=[("pop", DataType.LONG)],
    )
    cfg = TableConfig(table_name="cities")
    controller.add_table(cfg, schema)
    build_segment(
        schema,
        {"name": ["springfield", "shelbyville", "ogdenville", "o'brienville"],
         "pop": np.array([30000, 20000, 5000, 1000], dtype=np.int64)},
        str(tmp / "up"), cfg, "c0")
    controller.upload_segment("cities", str(tmp / "up"))
    assert wait_until(lambda: len(registry.external_view("cities_OFFLINE")) == 1)
    yield registry, broker, http
    http.stop()
    broker.close()
    server.stop()


class TestClient:
    def test_http_connection_fetch(self, cluster):
        registry, broker, http = cluster
        with connect(http.url) as conn:
            cur = conn.cursor()
            cur.execute("SELECT name, pop FROM cities ORDER BY pop DESC")
            assert cur.rowcount == 4
            assert [d[0] for d in cur.description] == ["name", "pop"]
            assert cur.fetchone() == ("springfield", 30000)
            assert cur.fetchmany(2) == [("shelbyville", 20000),
                                        ("ogdenville", 5000)]
            assert cur.fetchall() == [("o'brienville", 1000)]
            assert cur.fetchone() is None
            assert cur.stats["numDocsScanned"] >= 4

    def test_iteration_and_aggregate(self, cluster):
        registry, broker, http = cluster
        conn = connect(http.url)
        cur = conn.cursor().execute("SELECT SUM(pop) FROM cities")
        assert list(cur) == [(56000,)]
        conn.close()

    def test_qmark_params_quote_safely(self, cluster):
        registry, broker, http = cluster
        with connect(http.url) as conn:
            cur = conn.cursor()
            cur.execute("SELECT pop FROM cities WHERE name = ?",
                        ["o'brienville"])
            assert cur.fetchall() == [(1000,)]
            cur.execute("SELECT name FROM cities WHERE pop > ? ORDER BY name",
                        [19000])
            assert cur.fetchall() == [("shelbyville",), ("springfield",)]
            with pytest.raises(ProgrammingError, match="placeholders"):
                cur.execute("SELECT 1 FROM cities WHERE pop > ?", [1, 2])
            # empty params still validates placeholder count
            with pytest.raises(ProgrammingError, match="placeholders"):
                cur.execute("SELECT 1 FROM cities WHERE pop > ?", [])
            # ? inside a string literal is not a placeholder
            cur.execute("SELECT pop FROM cities WHERE name <> '?' "
                        "AND pop < ?", [2000])
            assert cur.fetchall() == [(1000,)]
            # ? inside a double-quoted identifier is not a placeholder (r3)
            from pinot_tpu.client import _split_placeholders

            assert _split_placeholders(
                'SELECT "what?" FROM t WHERE x = ?') == \
                ['SELECT "what?" FROM t WHERE x = ', '']

    def test_fetchmany_zero_returns_empty(self, cluster):
        registry, broker, http = cluster
        with connect(http.url) as conn:
            cur = conn.cursor().execute("SELECT name FROM cities")
            assert cur.fetchmany(0) == []
            assert len(cur.fetchall()) == 4

    def test_embedded_connection_over_registry(self, cluster):
        registry, broker, http = cluster
        with connect(registry=registry) as conn:
            cur = conn.cursor().execute("SELECT COUNT(*) FROM cities")
            assert cur.fetchall() == [(4,)]

    def test_wrapping_existing_broker(self, cluster):
        registry, broker, http = cluster
        conn = Connection(broker=broker)
        assert conn.cursor().execute(
            "SELECT MAX(pop) FROM cities").fetchone() == (30000,)
        conn.close()
        # wrapping does not own the broker: it keeps working
        assert broker.execute("SELECT COUNT(*) FROM cities")[
            "resultTable"]["rows"] == [[4]]

    def test_errors_raise_database_error(self, cluster):
        registry, broker, http = cluster
        with connect(http.url) as conn:
            cur = conn.cursor()
            with pytest.raises(DatabaseError):
                cur.execute("SELECT nosuch FROM cities")
            with pytest.raises(DatabaseError):
                cur.execute("SELECT COUNT(*) FROM nosuchtable")

    def test_closed_states(self, cluster):
        registry, broker, http = cluster
        conn = connect(http.url)
        cur = conn.cursor()
        with pytest.raises(ProgrammingError, match="fetch before execute"):
            cur.fetchall()
        cur.close()
        with pytest.raises(ProgrammingError, match="closed"):
            cur.execute("SELECT 1 FROM cities")
        with pytest.raises(ProgrammingError, match="closed"):
            cur.fetchall()  # use-after-close names the real bug (r3)
        conn.close()
        with pytest.raises(ProgrammingError, match="closed"):
            conn.cursor()

class TestBasicAuth:
    """Broker HTTP basic auth (BasicAuthAccessControlFactory analog)."""

    def test_auth_required_and_accepted(self, cluster, tmp_path):
        registry, broker, _ = cluster
        from pinot_tpu.broker.http_api import BrokerHttpServer

        http = BrokerHttpServer(broker, users={"admin": "s3cret"})
        http.start()
        try:
            # no credentials: 401 surfaces as a DatabaseError
            with connect(http.url) as conn:
                with pytest.raises(DatabaseError):
                    conn.cursor().execute("SELECT COUNT(*) FROM cities")
            # wrong password: rejected
            with connect(http.url, auth=("admin", "wrong")) as conn:
                with pytest.raises(DatabaseError):
                    conn.cursor().execute("SELECT COUNT(*) FROM cities")
            # correct credentials: served
            with connect(http.url, auth=("admin", "s3cret")) as conn:
                cur = conn.cursor().execute("SELECT COUNT(*) FROM cities")
                assert cur.fetchone() == (4,)
            # /health stays open; /metrics is gated (r3 review)
            import urllib.error
            import urllib.request

            with urllib.request.urlopen(http.url + "/health") as resp:
                assert resp.status == 200
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(http.url + "/metrics")
            assert ei.value.code == 401
        finally:
            http.stop()

    def test_non_ascii_password(self, cluster):
        registry, broker, _ = cluster
        from pinot_tpu.broker.http_api import BrokerHttpServer

        http = BrokerHttpServer(broker, users={"admin": "päss"})
        http.start()
        try:
            with connect(http.url, auth=("admin", "päss")) as conn:
                assert conn.cursor().execute(
                    "SELECT COUNT(*) FROM cities").fetchone() == (4,)
            with connect(http.url, auth=("admin", "wrong")) as conn:
                with pytest.raises(DatabaseError, match="authentication"):
                    conn.cursor().execute("SELECT COUNT(*) FROM cities")
        finally:
            http.stop()


class TestTableAcls:
    """Per-principal table ACLs (principals.<user>.tables= — the
    reference's BasicAuthAccessControlFactory.java:44 table-level grants)
    enforced at the broker query API and the controller admin REST."""

    @staticmethod
    def _post(url, sql, auth):
        import base64
        import json as _json
        import urllib.request

        req = urllib.request.Request(
            url + "/query/sql",
            data=_json.dumps({"sql": sql}).encode(),
            headers={"Authorization": "Basic " + base64.b64encode(
                f"{auth[0]}:{auth[1]}".encode()).decode()},
            method="POST")
        try:
            with urllib.request.urlopen(req) as resp:
                return resp.status, _json.loads(resp.read())
        except Exception as e:  # urllib raises on 4xx
            import urllib.error

            assert isinstance(e, urllib.error.HTTPError)
            return e.code, _json.loads(e.read())

    def test_broker_denies_unlisted_table(self, cluster):
        registry, broker, _ = cluster
        from pinot_tpu.broker.http_api import BrokerHttpServer

        http = BrokerHttpServer(
            broker,
            users={"admin": "root", "reader": "pw"},
            acls={"reader": ["cities"]})  # admin unrestricted
        http.start()
        try:
            # allowed table: served
            code, body = self._post(http.url, "SELECT COUNT(*) FROM cities",
                                    ("reader", "pw"))
            assert code == 200 and not body.get("exceptions"), body
            assert body["resultTable"]["rows"] == [[4]]
            # table outside the principal's list: 403 BEFORE execution
            code, body = self._post(
                http.url, "SELECT COUNT(*) FROM classified", ("reader", "pw"))
            assert code == 403, body
            assert body["exceptions"][0]["errorCode"] == 403
            # type suffix doesn't bypass the grant check
            code, _ = self._post(
                http.url, "SELECT COUNT(*) FROM cities_OFFLINE",
                ("reader", "pw"))
            assert code == 200
            # unrestricted principal still reaches everything
            code, body = self._post(http.url, "SELECT COUNT(*) FROM cities",
                                    ("admin", "root"))
            assert code == 200 and body["resultTable"]["rows"] == [[4]]
        finally:
            http.stop()

    def test_controller_rest_filters_tables(self, cluster):
        import base64
        import json as _json
        import urllib.error
        import urllib.request

        registry, _, _ = cluster
        from pinot_tpu.controller.http_api import ControllerHttpServer

        srv = ControllerHttpServer(
            registry,
            users={"admin": "root", "reader": "pw"},
            acls={"reader": ["somethingelse"]})
        srv.start()

        def get(path, auth=None):
            headers = {}
            if auth:
                headers["Authorization"] = "Basic " + base64.b64encode(
                    f"{auth[0]}:{auth[1]}".encode()).decode()
            req = urllib.request.Request(srv.url + path, headers=headers)
            try:
                with urllib.request.urlopen(req) as resp:
                    return resp.status, _json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, _json.loads(e.read() or b"{}")

        try:
            assert get("/health")[0] == 200  # open, like the reference
            assert get("/tables")[0] == 401  # auth required
            code, body = get("/tables", ("admin", "root"))
            assert code == 200 and "cities_OFFLINE" in body["tables"]
            # reader's grant list doesn't include cities: filtered out
            # (the ACL compares BASE names, so the typed key still matches)
            code, body = get("/tables", ("reader", "pw"))
            assert code == 200 and body["tables"] == []
            # ...and direct reads are denied before existence resolution
            code, _ = get("/tables/cities", ("reader", "pw"))
            assert code == 403
            code, body = get("/tables/cities", ("admin", "root"))
            assert code == 200 and body["config"]["table_name"] == "cities"
            assert get("/tables/nope", ("admin", "root"))[0] == 404
        finally:
            srv.stop()
