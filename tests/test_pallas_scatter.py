"""Pallas scatter-kernel tier differential suite (ISSUE 15).

Pins the tier three ways against its compiled-in references:
kernel-level (pallas_scatter primitives vs numpy oracles, partitioned
launches forced), engine-level (Pallas pipelines == XLA scatter
pipelines BIT-EXACT, == host within the established float tolerance —
across int64 two-stage sums, float accumulation, group-count
boundaries, sealed + consuming(chunklet), solo + 8-dev mesh, and
cohort-coalesced launches), and routing-level (PINOT_TPU_PALLAS=0 /
SET usePallas=false escape hatches, and the quarantine XLA rung that
keeps a Pallas-only failure on device).

All kernels run in interpret mode here (JAX_PLATFORMS=cpu) — the same
compiled structure the TPU executes, per the ops/groupby_mm.py pattern.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import (
    ChunkletConfig,
    IndexingConfig,
    TableConfig,
)
from pinot_tpu.engine.device import DeviceExecutor
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.ops import groupby_mm as mm
from pinot_tpu.ops import pallas_scatter as ps
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment


def _rows_close(rows_a, rows_b):
    if len(rows_a) != len(rows_b):
        return False
    for ra, rb in zip(rows_a, rows_b):
        for x, y in zip(ra, rb):
            if isinstance(x, str) or x is None:
                if x != y:
                    return False
            elif not np.isclose(float(x), float(y), rtol=1e-5, atol=1e-6):
                return False
    return True


# ---------------------------------------------------------------------------
# kernel-level: primitives vs numpy oracles
# ---------------------------------------------------------------------------


class TestPlaneGroupSums:
    def _check(self, G, span_hpad=None, n=20000):
        rng = np.random.default_rng(7)
        gid = rng.integers(0, G + 1, n).astype(np.int32)  # incl. overflow
        val = rng.integers(-100, 100, n).astype(np.int64)
        off = -128
        chans = jnp.stack(
            [jnp.ones(n, jnp.bfloat16)]
            + mm.int_planes(jnp.asarray(val), off, 2))
        out = ps.plane_group_sums(
            jnp.asarray(gid), chans, G, interpret=True,
            first_channel_ones=True, span_hpad=span_hpad)
        cnt = np.round(np.asarray(out[0])).astype(np.int64)
        np.testing.assert_array_equal(
            cnt, np.bincount(gid, minlength=G + 1)[:G])
        s = np.asarray(mm.recombine_int(
            [out[1], out[2]], jnp.asarray(cnt), jnp.int64(off)))
        ref = np.zeros(G + 1, dtype=np.int64)
        np.add.at(ref, gid, val)
        np.testing.assert_array_equal(s, ref[:G])

    @pytest.mark.parametrize("G", [1, 255, 300, 4096])
    def test_vs_numpy(self, G):
        self._check(G)

    def test_partitioned_multi_pass(self):
        # span_hpad=8 → 1024 groups per partition → 5 partitions
        self._check(5000, span_hpad=8)

    def test_supported_bounds(self):
        assert ps.sums_supported(1, 2)
        assert ps.sums_supported(1 << 20, 4)
        # the partition sweep is bounded: absurd G declines
        assert not ps.sums_supported(1 << 27, 15)


class TestGroupMinMax:
    def test_vs_numpy_int(self):
        rng = np.random.default_rng(8)
        G, n = 300, 20000
        gid = rng.integers(0, G + 1, n).astype(np.int32)
        val = rng.integers(-1000, 1000, n).astype(np.int32)
        mn, mx = ps.group_minmax(
            jnp.asarray(gid), jnp.asarray(val), G, ("min", "max"),
            interpret=True)
        refmn = np.full(G + 1, np.iinfo(np.int32).max, np.int64)
        refmx = np.full(G + 1, np.iinfo(np.int32).min, np.int64)
        np.minimum.at(refmn, gid, val)
        np.maximum.at(refmx, gid, val)
        np.testing.assert_array_equal(np.asarray(mn), refmn[:G])
        np.testing.assert_array_equal(np.asarray(mx), refmx[:G])

    def test_partitioned_and_fills(self):
        # G=5000 → 5 partitions; empty groups keep the caller's fill
        rng = np.random.default_rng(9)
        G, n = 5000, 8000
        gid = (rng.integers(0, G // 2, n) * 2).astype(np.int32)  # evens only
        val = rng.uniform(-5, 5, n).astype(np.float32)
        mx, = ps.group_minmax(
            jnp.asarray(gid), jnp.asarray(val), G, ("max",),
            interpret=True, fills=(float("-inf"),))
        got = np.asarray(mx)
        refmx = np.full(G, -np.inf, np.float64)
        np.maximum.at(refmx, gid, val.astype(np.float64))
        np.testing.assert_array_equal(got, refmx.astype(np.float32))
        assert np.isneginf(got[1::2]).all()  # odd groups empty

    def test_supported(self):
        assert ps.minmax_supported(8000, np.int32)
        assert ps.minmax_supported(100, np.float32)
        assert not ps.minmax_supported(100, np.int64)   # no 64-bit vectors
        assert not ps.minmax_supported(100, np.float64)
        assert not ps.minmax_supported(1 << 16, np.int32)  # span bound


class TestHllRegisterMax:
    @pytest.mark.parametrize("span_hpad", [None, 8])
    def test_vs_numpy(self, span_hpad):
        rng = np.random.default_rng(10)
        nslots, nrho, n = 2048, 23, 30000
        slot = rng.integers(0, nslots + 1, n).astype(np.int32)
        rho = rng.integers(1, nrho + 1, n).astype(np.int32)
        regs = ps.hll_register_max(
            jnp.asarray(slot), jnp.asarray(rho), nslots, nrho,
            interpret=True, span_hpad=span_hpad)
        ref = np.zeros(nslots + 1, np.int32)
        np.maximum.at(ref, slot, rho)
        np.testing.assert_array_equal(np.asarray(regs), ref[:nslots])

    def test_matches_scatter_max_build(self):
        """The engine contract: the kernel's registers equal the XLA
        f32 scatter-max registers for the same (slot, rho) stream."""
        from pinot_tpu.ops import hll as hll_ops

        rng = np.random.default_rng(11)
        log2m = 10
        m = 1 << log2m
        keys = rng.integers(0, 500, 50000).astype(np.int32)
        h = hll_ops.hash32(jnp.asarray(keys))
        idx, rho = hll_ops.hll_idx_rho(h, log2m)
        regs_scatter = np.asarray(
            jnp.zeros(m + 1, jnp.float32).at[idx].max(
                rho.astype(jnp.float32))[:m]).astype(np.int32)
        regs_pallas = np.asarray(ps.hll_register_max(
            idx, rho, m, mm.hll_nrho(log2m), interpret=True))
        np.testing.assert_array_equal(regs_pallas, regs_scatter)

    def test_supported_bound(self):
        assert ps.hll_supported(1 << 10, 23)
        assert not ps.hll_supported(ps.HLL_MAX_SLOTS * 2, 23)


class TestFusedPlan:
    WIDTHS = {
        "d": ("uint8", 0, False, None),
        "iv": ("uint16", 0, True, "int64"),
        "fv": ("float32", 0, False, None),
        "sb": ("uint8", 4, False, None),  # sub-byte packed
    }
    RANGE = ("range_raw", ("raw", "iv"), "p1", "p2", True, True, True, False)

    def test_eligible(self):
        plan = ps.plan_fused(
            ("and", ("eq_dict", "d", "p0"), self.RANGE),
            (("count", None, None), ("sum", ("raw", "iv"), (2, 1 << 20)),
             ("minmaxrange", ("raw", "fv"), None)),
            self.WIDTHS)
        assert plan is not None
        assert plan.n_int == 2 and plan.n_flt == 2
        assert set(plan.pred_params) == {"p0", "p1", "p2"}

    def test_ineligible_shapes(self):
        count = (("count", None, None),)
        # sub-byte plane
        assert ps.plan_fused(("eq_dict", "sb", "p0"), count,
                             self.WIDTHS) is None
        # regex LUT node
        assert ps.plan_fused(("lut_dict", "d", "p0"), count,
                             self.WIDTHS) is None
        # float raw predicate (literal rounding would change compares)
        assert ps.plan_fused(
            ("range_raw", ("raw", "fv"), "p1", "p2", True, True, True,
             False), count, self.WIDTHS) is None
        # float SUM (order-sensitive accumulation stays on XLA)
        assert ps.plan_fused(
            ("eq_dict", "d", "p0"),
            (("sum", ("raw", "fv"), (None, None)),), self.WIDTHS) is None
        # int SUM whose per-block partial could overflow int32
        assert ps.plan_fused(
            ("eq_dict", "d", "p0"),
            (("sum", ("raw", "iv"), (2, 2048)),), self.WIDTHS) is None

    def test_params_ok_bounds_in_lists(self):
        plan = ps.plan_fused(("in_dict", "d", "p0"), (("count", None, None),),
                             self.WIDTHS)
        assert plan is not None
        assert ps.fused_params_ok(plan, {"p0": jnp.zeros(4, jnp.int32)})
        assert not ps.fused_params_ok(
            plan, {"p0": jnp.zeros(ps.FUSED_MAX_IN + 1, jnp.int32)})
        assert not ps.fused_params_ok(plan, {})


# ---------------------------------------------------------------------------
# engine-level differential: pallas == XLA scatter == host
# ---------------------------------------------------------------------------


def _build_table(base, seed=5, n=30000, card=220):
    """3 segments; ``ts`` ascends globally (time-ordered layout — the
    shape zone maps discriminate on; span < 65536 keeps its
    frame-of-reference plane uint16, inside the fused kernel's predicate
    surface), everything else unclustered."""
    rng = np.random.default_rng(seed)
    assert n < 65536
    cols = {
        "ts": np.arange(n, dtype=np.int64),
        "d": np.array([f"k{i:04d}" for i in range(card)])[
            rng.integers(0, card, n)],
        "e": np.array(["x", "y", "z"])[rng.integers(0, 3, n)],
        "iv": rng.integers(0, 9000, n).astype(np.int32),
        # int64 values past 2^31: exercises the two-stage exact sum planes
        "big": (rng.integers(0, 1 << 38, n)).astype(np.int64),
        "fv": rng.uniform(-100, 100, n).astype(np.float64),
    }
    schema = Schema.build(
        name="t",
        dimensions=[("ts", DataType.LONG), ("d", DataType.STRING),
                    ("e", DataType.STRING)],
        metrics=[("iv", DataType.INT), ("big", DataType.LONG),
                 ("fv", DataType.DOUBLE)],
    )
    cfg = TableConfig(table_name="t",
                      indexing=IndexingConfig(no_dictionary_columns=["ts"]))
    segs = []
    third = n // 3
    for i, sl in enumerate([slice(0, third), slice(third, 2 * third),
                            slice(2 * third, n)]):
        part = {k: v[sl] for k, v in cols.items()}
        build_segment(schema, part, str(base / f"s{i}"), cfg, f"s{i}")
        segs.append(ImmutableSegment(str(base / f"s{i}")))
    return segs, cols


@pytest.fixture(scope="module")
def engines(tmp_path_factory):
    base = tmp_path_factory.mktemp("pallas_seg")
    segs, cols = _build_table(base)
    pallas = QueryEngine(device_executor=DeviceExecutor(mm_mode="interpret"))
    xla = QueryEngine(device_executor=DeviceExecutor(
        mm_mode="interpret", pallas_mode="off"))
    host = QueryEngine(device_executor=None)
    for e in (pallas, xla, host):
        for s in segs:
            e.add_segment("t", s)
    return pallas, xla, host, cols


DIFF_QUERIES = [
    # int64 two-stage sums (values past 2^31 → multi-plane exact path)
    "SELECT d, SUM(big), COUNT(*) FROM t GROUP BY d ORDER BY d LIMIT 250",
    # float accumulation (3-way bf16 split planes)
    "SELECT d, SUM(fv), AVG(fv) FROM t GROUP BY d ORDER BY d LIMIT 250",
    # min/max scatter family (no MXU identity)
    "SELECT d, MIN(iv), MAX(iv), MINMAXRANGE(big) FROM t "
    "GROUP BY d ORDER BY d LIMIT 250",
    # scalar HLL: the register-max scatter
    "SELECT DISTINCTCOUNTHLL(d) FROM t",
    "SELECT DISTINCTCOUNTHLL(d) FROM t WHERE e = 'x'",
    # fused filter+gather+aggregate shapes (selective time range → the
    # block-skip SKIP branch actually executes: one candidate block)
    "SELECT COUNT(*) FROM t WHERE ts < 40",
    "SELECT COUNT(*), SUM(iv), MIN(iv), MAX(iv) FROM t WHERE ts BETWEEN "
    "100 AND 700",
    "SELECT COUNT(*), MAX(fv), SUM(big) FROM t WHERE ts >= 59000",
    "SELECT COUNT(*), MIN(fv) FROM t WHERE ts < 3000 AND d = 'k0003'",
    "SELECT COUNT(*) FROM t WHERE d IN ('k0001','k0007') AND e = 'y'",
    # NOT node rides the fused kernel too (~child in-kernel)
    "SELECT COUNT(*), SUM(iv) FROM t WHERE NOT e = 'x' AND ts < 300",
    # float SUM: fused-ineligible (order-sensitive) → generic gather branch
    "SELECT COUNT(*), SUM(fv) FROM t WHERE ts < 300",
    # dense + group-by over two keys
    "SELECT d, e, COUNT(*), SUM(iv) FROM t GROUP BY d, e "
    "ORDER BY d, e LIMIT 100",
]


@pytest.mark.parametrize("sql", DIFF_QUERIES)
def test_pallas_xla_host_parity(engines, sql):
    pallas, xla, host, _ = engines
    rp, rx, rh = pallas.execute(sql), xla.execute(sql), host.execute(sql)
    for r in (rp, rx, rh):
        assert not r.get("exceptions"), (sql, r)
    # the two device paths are BIT-exact (order-independent kernels)
    assert rp["resultTable"]["rows"] == rx["resultTable"]["rows"], (
        sql, rp["resultTable"]["rows"][:4], rx["resultTable"]["rows"][:4])
    # host compares at the established float tolerance (device floats
    # live in the f32 value space)
    assert _rows_close(rp["resultTable"]["rows"], rh["resultTable"]["rows"]), (
        sql, rp["resultTable"]["rows"][:4], rh["resultTable"]["rows"][:4])


def test_fractional_literal_declines_fused(engines):
    """Review regression: a fractional literal over an integer column
    must NOT enter the fused kernel (the storage-space int cast would
    truncate it while the generic branch compares with float promotion).
    The plan declines via fused_params_ok and all three paths agree."""
    pallas, xla, host, _ = engines
    for sql in ("SELECT COUNT(*) FROM t WHERE ts < 10.5",
                "SELECT COUNT(*), SUM(iv) FROM t WHERE ts BETWEEN 99.5 "
                "AND 700.5"):
        rp, rx, rh = pallas.execute(sql), xla.execute(sql), host.execute(sql)
        for r in (rp, rx, rh):
            assert not r.get("exceptions"), (sql, r)
        assert rp["resultTable"]["rows"] == rx["resultTable"]["rows"] \
            == rh["resultTable"]["rows"], (
                sql, rp["resultTable"]["rows"], rx["resultTable"]["rows"],
                rh["resultTable"]["rows"])


def test_label_only_when_tier_routes(engines):
    """Review regression: the "+pallas" roofline label claims the tier
    only for pipelines that actually compile a Pallas kernel — a scalar
    shape with no HLL (its min/max/sum are dense reductions, not
    scatters) must keep its XLA label even with the tier enabled."""
    pallas, _, _, _ = engines
    r = pallas.execute(
        "SET usePartialsCache=false; SELECT COUNT(*), SUM(fv) FROM t "
        "WHERE ts < 300")
    recs = [rec.get("kernel", "") for rec in (r.get("roofline") or [])]
    assert recs and all("+pallas" not in k and "+fused" not in k
                        for k in recs), recs


def test_pallas_pipelines_and_labels(engines):
    """The tier actually ran: pallas-keyed pipelines compiled and the
    roofline attributes them under their own labels."""
    pallas, _, _, _ = engines
    pallas.execute("SELECT d, MIN(iv) FROM t GROUP BY d LIMIT 5")
    modes = {k[5] for k in pallas.device._pipelines}
    assert "interpret" in modes
    labels = set(pallas.device.roofline_stats()["kernels"])
    assert any("+pallas" in lb for lb in labels), labels


def test_fused_label_and_gather_model(engines):
    """A selective fused query earns the +fused label, actually prunes
    blocks, and its roofline record does NOT carry the gather round-trip
    term the XLA form pays."""
    pallas, xla, _, _ = engines
    r = pallas.execute(
        "SET usePartialsCache=false; SELECT COUNT(*), SUM(iv) FROM t "
        "WHERE ts < 25")
    assert r["numBlocksPruned"] > 0, r  # the skip branch really ran
    labels = set(pallas.device.roofline_stats()["kernels"])
    assert any("+fused" in lb for lb in labels), labels
    recs = [rec for rec in (r.get("roofline") or [])
            if "+fused" in rec.get("kernel", "")]
    assert recs and all("gatherBytes" not in rec for rec in recs), \
        r.get("roofline")
    # the XLA form of the same query pays the gather round trip
    rx = xla.execute(
        "SET usePartialsCache=false; SELECT COUNT(*), SUM(iv) FROM t "
        "WHERE ts < 25")
    assert rx["numBlocksPruned"] > 0, rx
    xrecs = [rec for rec in (rx.get("roofline") or [])
             if "bskip" in rec.get("kernel", "") and not rec.get("cacheHit")]
    assert any(rec.get("gatherBytes") for rec in xrecs), rx.get("roofline")


class TestGroupCountBoundaries:
    @pytest.mark.parametrize("card", [1, 255, 65536])
    def test_boundary_cardinality(self, tmp_path, card):
        rng = np.random.default_rng(card)
        n = max(4000, card)
        vals = np.arange(card)
        d = vals[rng.integers(0, card, n - card)] if n > card else vals
        d = np.concatenate([vals, d])[:n]  # every id present
        cols = {"d": np.array([f"v{i:06d}" for i in range(card)])[d],
                "m": rng.integers(0, 100, n).astype(np.int32)}
        schema = Schema.build(name="b",
                              dimensions=[("d", DataType.STRING)],
                              metrics=[("m", DataType.INT)])
        build_segment(schema, cols, str(tmp_path / "s0"),
                      TableConfig(table_name="b"), "s0")
        seg = ImmutableSegment(str(tmp_path / "s0"))
        pallas = QueryEngine(
            device_executor=DeviceExecutor(mm_mode="interpret"))
        xla = QueryEngine(device_executor=DeviceExecutor(
            mm_mode="interpret", pallas_mode="off"))
        pallas.add_segment("b", seg)
        xla.add_segment("b", seg)
        sql = ("SELECT d, COUNT(*), SUM(m), MIN(m) FROM b GROUP BY d "
               "ORDER BY d LIMIT 20")
        rp, rx = pallas.execute(sql), xla.execute(sql)
        assert not rp.get("exceptions") and not rx.get("exceptions"), rp
        assert rp["resultTable"]["rows"] == rx["resultTable"]["rows"]

    def test_num_groups_limit_overflow_policy_unchanged(self, tmp_path):
        """numGroupsLimit pressure: the Pallas tier must not change the
        dense regime's deterministic gid-order drop policy — pallas and
        XLA device paths drop identically and both flag the limit."""
        rng = np.random.default_rng(1)
        n = 5000
        cols = {"d": np.array([f"v{i:04d}" for i in range(900)])[
            rng.integers(0, 900, n)],
            "m": rng.integers(0, 100, n).astype(np.int32)}
        schema = Schema.build(name="b", dimensions=[("d", DataType.STRING)],
                              metrics=[("m", DataType.INT)])
        build_segment(schema, cols, str(tmp_path / "s0"),
                      TableConfig(table_name="b"), "s0")
        seg = ImmutableSegment(str(tmp_path / "s0"))
        pallas = QueryEngine(
            device_executor=DeviceExecutor(mm_mode="interpret"))
        xla = QueryEngine(device_executor=DeviceExecutor(
            mm_mode="interpret", pallas_mode="off"))
        pallas.add_segment("b", seg)
        xla.add_segment("b", seg)
        sql = ("SET numGroupsLimit=50; SELECT d, SUM(m) FROM b GROUP BY d "
               "ORDER BY d LIMIT 900")
        rp, rx = pallas.execute(sql), xla.execute(sql)
        assert rp["resultTable"]["rows"] == rx["resultTable"]["rows"]
        assert rp["numGroupsLimitReached"] and rx["numGroupsLimitReached"]


def test_consuming_chunklet_parity(tmp_path):
    """Promoted chunklets ride the Pallas pipelines like sealed segments;
    answers match the all-host scan and the XLA device form bit-exactly."""
    from pinot_tpu.storage.mutable import MutableSegment

    schema = Schema.build(
        name="rt", dimensions=[("tag", DataType.STRING)],
        metrics=[("m", DataType.INT), ("big", DataType.LONG)])
    cfg = TableConfig(
        table_name="rt",
        chunklets=ChunkletConfig(enabled=True, rows_per_chunklet=8192,
                                 device_min_rows=8192))
    rng = np.random.default_rng(41)
    n = 20000
    tags = np.array([f"t{i:02d}" for i in range(40)])[rng.integers(0, 40, n)]
    ms = rng.integers(0, 1000, n)
    bigs = rng.integers(0, 1 << 36, n)
    rows = [{"tag": str(t), "m": int(v), "big": int(b)}
            for t, v, b in zip(tags, ms, bigs)]
    seg = MutableSegment(schema, "rt__0__0__0", cfg)
    for i in range(0, n, 8192):
        seg.index_batch(rows[i:i + 8192])
        seg.chunklet_index.promote()
    assert seg.chunklet_index.chunklets, "no chunklets promoted"

    pallas = QueryEngine(device_executor=DeviceExecutor(mm_mode="interpret"))
    xla = QueryEngine(device_executor=DeviceExecutor(
        mm_mode="interpret", pallas_mode="off"))
    host = QueryEngine(device_executor=None)
    for e in (pallas, xla, host):
        e.add_segment("rt", seg)
    for sql in (
        "SELECT tag, COUNT(*), SUM(big), MIN(m), MAX(m) FROM rt "
        "GROUP BY tag ORDER BY tag LIMIT 50",
        "SELECT DISTINCTCOUNTHLL(tag) FROM rt WHERE m < 500",
    ):
        rp, rx, rh = pallas.execute(sql), xla.execute(sql), host.execute(sql)
        assert not rp.get("exceptions"), rp
        assert rp["resultTable"]["rows"] == rx["resultTable"]["rows"], sql
        assert _rows_close(rp["resultTable"]["rows"],
                           rh["resultTable"]["rows"]), sql
    assert any(k[5] == "interpret" for k in pallas.device._pipelines)


def test_mesh_parity(tmp_path):
    """8-dev mesh: sharded Pallas pipelines combine to the same answers
    as the solo launch (psum/pmax of the same order-independent
    accumulators)."""
    from pinot_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) == 8, "conftest must provide 8 devices"
    segs, _ = _build_table(tmp_path, seed=6, n=12000, card=60)
    mesh = make_mesh(8)
    sharded = QueryEngine(device_executor=DeviceExecutor(
        mesh=mesh, mm_mode="interpret"))
    solo = QueryEngine(device_executor=DeviceExecutor(mm_mode="interpret"))
    for e in (sharded, solo):
        for s in segs:
            e.add_segment("t", s)
    for sql in (
        "SELECT d, COUNT(*), SUM(big), MIN(iv), MAX(iv) FROM t "
        "GROUP BY d ORDER BY d LIMIT 80",
        "SELECT DISTINCTCOUNTHLL(d) FROM t WHERE e != 'z'",
    ):
        rs, r1 = sharded.execute(sql), solo.execute(sql)
        assert not rs.get("exceptions"), rs
        assert rs["resultTable"]["rows"] == r1["resultTable"]["rows"], sql


def test_cohort_coalesced_parity(engines):
    """Cohort-coalesced launches (vmapped pipeline, dense form) over the
    Pallas tier equal their solo executions."""
    pallas, _, _, _ = engines
    sqls = [
        f"SELECT d, COUNT(*), SUM(iv), MIN(iv) FROM t WHERE iv > {lit} "
        "GROUP BY d ORDER BY SUM(iv) DESC, d LIMIT 10"
        for lit in (500, 2500, 4500, 6500)
    ]
    expected = [pallas.execute(s)["resultTable"]["rows"] for s in sqls]
    pallas.device.partials_cache_enabled = False
    co = pallas.device.coalescer
    co.force = True
    co.window_s = 0.05
    co.max_cohort = 4
    c0 = co.queries_coalesced
    try:
        barrier = threading.Barrier(len(sqls))
        got = [None] * len(sqls)

        def worker(i):
            barrier.wait()
            got[i] = pallas.execute(sqls[i])["resultTable"]["rows"]

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(len(sqls))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        co.force = False
        pallas.device.partials_cache_enabled = True
    assert co.queries_coalesced > c0, "no query joined a cohort"
    for s, g, e in zip(sqls, got, expected):
        assert g == e, (s, g, e)


# ---------------------------------------------------------------------------
# routing: escape hatches + the quarantine XLA rung
# ---------------------------------------------------------------------------


class TestRouting:
    def test_env_kill_switch(self, engines, monkeypatch):
        pallas, _, _, _ = engines
        sql = "SELECT d, MIN(iv) FROM t GROUP BY d ORDER BY d LIMIT 7"
        want = pallas.execute(sql)["resultTable"]["rows"]
        monkeypatch.setenv("PINOT_TPU_PALLAS", "0")
        r = pallas.execute(sql)
        assert r["resultTable"]["rows"] == want
        # the forced-off execution compiled the XLA variant alongside
        assert any(k[5] == "off" for k in pallas.device._pipelines)

    def test_set_option_off_and_coexistence(self, engines):
        pallas, _, _, _ = engines
        sql = "SELECT e, MAX(iv) FROM t GROUP BY e ORDER BY e"
        r_on = pallas.execute(sql)
        r_off = pallas.execute("SET usePallas=false; " + sql)
        assert r_on["resultTable"]["rows"] == r_off["resultTable"]["rows"]
        tpls = {(k[0], k[5]) for k in pallas.device._pipelines}
        # both variants live in the cache for the same template
        both = {t for t, _m in tpls if (t, "interpret") in tpls
                and (t, "off") in tpls}
        assert both, tpls

    def test_zero_pallas_template_failure_skips_the_rung(self, tmp_path):
        """Review regression: a device failure on a template that routes
        NOTHING to the tier (scalar shape, no HLL) must take the normal
        XLA retry + host-quarantine strike path — not burn a Pallas-rung
        drop that recompiles a byte-identical pipeline and skips the
        strike."""
        from pinot_tpu.common import faults

        faults.clear()
        try:
            segs, _ = _build_table(tmp_path, seed=13, n=6000, card=20)
            eng = QueryEngine(
                device_executor=DeviceExecutor(mm_mode="interpret"))
            for s in segs:
                eng.add_segment("t", s)
            # float SUM with a filter: runs on device (a filterless
            # scalar agg answers from metadata) but is fused-ineligible
            # and scalar — zero Pallas kernels compile for it
            sql = "SELECT SUM(fv) FROM t WHERE e = 'x'"
            want = eng.execute(sql)["resultTable"]["rows"]
            faults.install(faults.Fault(point="device.launch",
                                        mode="error", times=1))
            r = eng.execute(sql + " LIMIT 1")
            assert not r.get("exceptions"), r
            assert r["resultTable"]["rows"] == want
            stats = eng.device.hbm_stats()
            assert stats["pallas_fallbacks"] == 0, stats
            assert stats["pallas_quarantined"] == 0, stats
            assert stats["device_failures"] == 1, stats
        finally:
            faults.clear()

    def test_pallas_failure_drops_to_xla_rung_on_device(self, tmp_path):
        """A device-runtime failure on a Pallas pipeline blocks only the
        Pallas rung: the launch retries the XLA scatter form ON DEVICE in
        the same call, no host-quarantine strike is recorded, and the
        (template, batch) pair keeps answering from the device."""
        from pinot_tpu.common import faults

        faults.clear()
        try:
            segs, _ = _build_table(tmp_path, seed=12, n=6000, card=30)
            eng = QueryEngine(
                device_executor=DeviceExecutor(mm_mode="interpret"))
            for s in segs:
                eng.add_segment("t", s)
            sql = ("SELECT d, SUM(iv), MIN(iv) FROM t GROUP BY d "
                   "ORDER BY d LIMIT 30")
            want = eng.execute(sql)["resultTable"]["rows"]
            dev = eng.device
            # next device launch fails once (the Pallas attempt)
            faults.install(faults.Fault(point="device.launch",
                                        mode="error", times=1))
            r = eng.execute(sql + " OFFSET 0")  # same template, fresh SQL
            assert not r.get("exceptions"), r
            assert r["resultTable"]["rows"] == want
            stats = dev.hbm_stats()
            assert stats["pallas_fallbacks"] >= 1
            assert stats["pallas_quarantined"] >= 1
            # the XLA rung kept the query ON DEVICE: no host quarantine
            assert stats["quarantined_pipelines"] == 0
            # and the rung's pipeline is the off-variant
            assert any(k[5] == "off" for k in dev._pipelines)
            # recovery: reset clears the rung; the Pallas form returns
            dev.reset_quarantine()
            assert dev.hbm_stats()["pallas_quarantined"] == 0
            r2 = eng.execute(sql)
            assert r2["resultTable"]["rows"] == want
        finally:
            faults.clear()
