"""Cluster integration: controller + servers + broker over real gRPC.

Reference analogs: pinot-integration-test-base ClusterTest (all roles in one
process, real transport), OfflineClusterIntegrationTest (push segments,
query via broker), MultiNodesOfflineClusterIntegrationTest, LLCRealtime-
ClusterIntegrationTest (stream → consuming → commit → broker-visible),
ChaosMonkey-style server kill with partial results, rebalance, retention.
"""

import time

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.cluster.registry import ClusterRegistry, Role, SegmentState
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import StreamConfig, TableConfig, TableType
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.stream.memory_stream import TopicRegistry


def wait_until(cond, timeout=10.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def cluster(tmp_path):
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "deepstore"))
    servers = [
        ServerInstance(f"server_{i}", registry, str(tmp_path / f"srv{i}"),
                       device_executor=None)
        for i in range(3)
    ]
    for s in servers:
        s.start()
    broker = Broker(registry, timeout_s=10.0)
    yield registry, controller, servers, broker
    broker.close()
    for s in servers:
        try:
            s.stop()
        except Exception:
            pass


def _offline_table(tmp_path, controller, n_segments=4, rows=2000, replication=2):
    schema = Schema.build(
        name="sales",
        dimensions=[("region", DataType.STRING), ("product", DataType.STRING)],
        metrics=[("amount", DataType.INT)],
    )
    cfg = TableConfig(table_name="sales", replication=replication)
    controller.add_table(cfg, schema)
    rng = np.random.default_rng(9)
    all_cols = []
    for i in range(n_segments):
        cols = {
            "region": np.array(["na", "eu", "apac"])[rng.integers(0, 3, rows)],
            "product": np.array([f"p{j}" for j in range(50)])[rng.integers(0, 50, rows)],
            "amount": rng.integers(1, 500, rows).astype(np.int32),
        }
        all_cols.append(cols)
        d = str(tmp_path / f"upload_s{i}")
        build_segment(schema, cols, d, cfg, f"sales_s{i}")
        controller.upload_segment("sales", d)
    return schema, cfg, all_cols


class TestOfflineCluster:
    def test_push_and_query(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _, _, all_cols = _offline_table(tmp_path, controller)
        # servers pick up assignments via sync loop
        assert wait_until(lambda: sum(
            len(s.engine.tables.get("sales_OFFLINE").segments) if s.engine.tables.get("sales_OFFLINE") else 0
            for s in servers
        ) >= 8)  # 4 segments x 2 replicas

        total = sum(int(c["amount"].sum()) for c in all_cols)
        r = broker.execute("SELECT COUNT(*), SUM(amount) FROM sales")
        assert not r["exceptions"], r
        assert r["resultTable"]["rows"][0] == [8000, total]
        assert r["numServersResponded"] >= 1
        # every segment counted exactly once despite replication
        assert r["numSegmentsQueried"] == 4
        # case-insensitive table resolution at the broker
        # (BaseBrokerRequestHandler.java:245-254 / TableCache ignore-case)
        for variant in ("SALES", "Sales", "sAlEs_OFFLINE"):
            r2 = broker.execute(f"SELECT COUNT(*) FROM {variant}")
            assert not r2["exceptions"], (variant, r2)
            assert r2["resultTable"]["rows"][0][0] == 8000

    def test_group_by_through_broker(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _, _, all_cols = _offline_table(tmp_path, controller)
        assert wait_until(lambda: len(registry.external_view("sales_OFFLINE")) == 4)
        r = broker.execute(
            "SELECT region, COUNT(*), SUM(amount) FROM sales GROUP BY region ORDER BY region"
        )
        assert not r["exceptions"], r
        import collections

        want = collections.Counter()
        wsum = collections.Counter()
        for c in all_cols:
            for reg, amt in zip(c["region"], c["amount"]):
                want[reg] += 1
                wsum[reg] += int(amt)
        got = {row[0]: (row[1], row[2]) for row in r["resultTable"]["rows"]}
        assert got == {k: (want[k], wsum[k]) for k in want}

    def test_server_death_partial_results(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, replication=1)
        assert wait_until(lambda: len(registry.external_view("sales_OFFLINE")) == 4)
        ok = broker.execute("SELECT COUNT(*) FROM sales")
        assert not ok["exceptions"]
        # kill one server hard (ChaosMonkey): with replication=1 its segments
        # are lost → partial results + SERVER_NOT_RESPONDING exception
        victim = next(
            s for s in servers if registry.assigned_segments(s.instance_id)
        )
        victim.transport.stop(grace=0)
        r = broker.execute("SELECT COUNT(*) FROM sales")
        assert r.get("partialResult") is True
        assert any("SERVER_NOT_RESPONDING" in e["message"] for e in r["exceptions"])
        assert r["resultTable"]["rows"][0][0] < 8000  # partial data

    def test_failover_with_replication(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, replication=2)
        assert wait_until(lambda: sum(
            len(v) for v in registry.external_view("sales_OFFLINE").values()
        ) >= 8)
        victim = next(s for s in servers if registry.assigned_segments(s.instance_id))
        victim.transport.stop(grace=0)
        # first query may be partial (failure detected); retried queries
        # route around the dead server to the surviving replicas
        deadline = time.time() + 10
        while time.time() < deadline:
            r = broker.execute("SELECT COUNT(*) FROM sales")
            if not r.get("exceptions") and r["resultTable"]["rows"][0][0] == 8000:
                break
            time.sleep(0.1)
        assert r["resultTable"]["rows"][0][0] == 8000, r

    def test_rebalance_after_server_join(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, replication=1)
        late = ServerInstance("server_late", registry, str(tmp_path / "late"),
                              device_executor=None)
        late.start()
        try:
            mapping = controller.rebalance("sales")
            hosts = {i for insts in mapping.values() for i in insts}
            # late server participates after rebalance OR load stays balanced
            counts = {}
            for insts in mapping.values():
                for i in insts:
                    counts[i] = counts.get(i, 0) + 1
            assert max(counts.values()) - min(counts.values()) <= 1
            assert wait_until(
                lambda: broker.execute("SELECT COUNT(*) FROM sales")
                .get("resultTable", {}).get("rows", [[0]])[0][0] == 8000
            )
        finally:
            late.stop()

    def test_retention(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        schema = Schema.build(
            name="logs",
            dimensions=[("k", DataType.STRING)],
            metrics=[("v", DataType.INT)],
            datetimes=[("ts", DataType.LONG)],
        )
        cfg = TableConfig(table_name="logs", retention_days=7, time_column="ts")
        controller.add_table(cfg, schema)
        now = int(time.time() * 1000)
        old_ts = now - 30 * 86_400_000
        for name, ts in (("old", old_ts), ("new", now)):
            d = str(tmp_path / f"logs_{name}")
            build_segment(
                schema,
                {"k": ["a"] * 10, "v": list(range(10)), "ts": [ts] * 10},
                d, cfg, f"logs_{name}",
            )
            controller.upload_segment("logs", d)
        assert len(registry.segments("logs_OFFLINE")) == 2
        dropped = controller.run_retention()
        assert ("logs_OFFLINE", "logs_old") in dropped
        assert "logs_new" in registry.segments("logs_OFFLINE")


class TestRealtimeCluster:
    def test_stream_to_broker_visibility(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        TopicRegistry.delete("clicks")
        topic = TopicRegistry.create("clicks", 2)
        schema = Schema.build(
            name="clicks",
            dimensions=[("page", DataType.STRING)],
            metrics=[("n", DataType.INT)],
        )
        cfg = TableConfig(
            table_name="clicks", table_type=TableType.REALTIME,
            stream=StreamConfig(
                stream_type="memory", topic="clicks", decoder="json",
                segment_flush_threshold_rows=60, segment_flush_threshold_seconds=3600,
            ),
        )
        controller.add_table(cfg, schema)
        for i in range(200):
            topic.publish_json({"page": f"page{i % 8}", "n": 1}, partition=i % 2)

        def broker_count():
            r = broker.execute("SELECT COUNT(*) FROM clicks")
            if r.get("exceptions"):
                return -1
            return r["resultTable"]["rows"][0][0]

        assert wait_until(lambda: broker_count() == 200, timeout=15), broker_count()
        # commits happened and sealed segments are registered ONLINE
        assert wait_until(lambda: any(
            rec.state == SegmentState.ONLINE
            for rec in registry.segments("clicks_REALTIME").values()
        ))
        r = broker.execute(
            "SELECT page, COUNT(*) FROM clicks GROUP BY page ORDER BY page LIMIT 10"
        )
        assert [row[1] for row in r["resultTable"]["rows"]] == [25] * 8


    def test_kill_consuming_server_no_loss(self, cluster, tmp_path):
        """Multi-replica consumption survives a consumer death: the replica
        keeps serving, the controller re-homes the dead server's partitions,
        and every row stays queryable exactly once (SegmentCompletionManager
        + RealtimeSegmentValidationManager semantics)."""
        registry, controller, servers, broker = cluster
        TopicRegistry.delete("mrclicks")
        topic = TopicRegistry.create("mrclicks", 1)
        schema = Schema.build(
            name="mrclicks",
            dimensions=[("page", DataType.STRING)],
            metrics=[("n", DataType.INT)],
        )
        cfg = TableConfig(
            table_name="mrclicks", table_type=TableType.REALTIME, replication=2,
            stream=StreamConfig(
                stream_type="memory", topic="mrclicks", decoder="json",
                segment_flush_threshold_rows=40, segment_flush_threshold_seconds=3600,
            ),
        )
        controller.add_table(cfg, schema)
        pa = registry.partition_assignment("mrclicks_REALTIME")
        assert all(len(v) == 2 for v in pa.values())

        def broker_count():
            r = broker.execute("SELECT COUNT(*) FROM mrclicks")
            if r.get("exceptions"):
                return -1
            return r["resultTable"]["rows"][0][0]

        for i in range(100):
            topic.publish_json({"page": f"p{i % 4}", "n": 1})
        assert wait_until(lambda: broker_count() == 100, timeout=20), broker_count()

        # kill one of the consuming replicas hard, mid-stream
        victims = set(pa["0"])
        victim = next(s for s in servers if s.instance_id in victims)
        victim.transport.stop(grace=0)
        victim._stop.set()  # sync loop (and its consumers' publishes) halt
        for mgr in victim._realtime_managers.values():
            mgr.stop(commit_remaining=False)
        for i in range(100):
            topic.publish_json({"page": f"p{i % 4}", "n": 1})
        controller.run_realtime_repair()

        deadline = time.time() + 20
        count = -1
        while time.time() < deadline:
            count = broker_count()
            if count == 200:
                break
            time.sleep(0.1)
        assert count == 200, count
        r = broker.execute(
            "SELECT page, COUNT(*) FROM mrclicks GROUP BY page ORDER BY page"
        )
        assert [row[1] for row in r["resultTable"]["rows"]] == [50] * 4


class TestHybridTable:
    def test_time_boundary_split(self, cluster, tmp_path):
        """Hybrid table: offline covers old time range, realtime covers new;
        the broker splits at the boundary so overlapping rows dedupe
        (TimeBoundaryManager + BaseBrokerRequestHandler.java:387-395)."""
        registry, controller, servers, broker = cluster
        schema = Schema.build(
            name="metrics",
            dimensions=[("host", DataType.STRING)],
            metrics=[("v", DataType.INT)],
            datetimes=[("ts", DataType.LONG)],
        )
        off_cfg = TableConfig(table_name="metrics", time_column="ts")
        controller.add_table(off_cfg, schema)
        # offline segment: ts 0..99 (100 rows)
        d = str(tmp_path / "metrics_off")
        build_segment(
            schema,
            {"host": ["h1"] * 100, "v": [1] * 100, "ts": list(range(100))},
            d, off_cfg, "metrics_off_0",
        )
        controller.upload_segment("metrics", d)

        TopicRegistry.delete("metrics_stream")
        topic = TopicRegistry.create("metrics_stream", 1)
        rt_cfg = TableConfig(
            table_name="metrics", table_type=TableType.REALTIME, time_column="ts",
            stream=StreamConfig(
                stream_type="memory", topic="metrics_stream", decoder="json",
                segment_flush_threshold_rows=10_000,
                segment_flush_threshold_seconds=3600,
            ),
        )
        controller.add_table(rt_cfg, schema)
        # realtime overlaps offline for ts 80..99 (late replay), then extends
        for ts in range(80, 150):
            topic.publish_json({"host": "h1", "v": 1, "ts": ts})

        def total():
            r = broker.execute("SELECT COUNT(*) FROM metrics")
            if r.get("exceptions"):
                return -1
            return r["resultTable"]["rows"][0][0]

        # boundary = offline max ts (99): offline answers ts<=99 (100 rows),
        # realtime answers ts>99 (50 rows) — overlap NOT double counted
        assert wait_until(lambda: total() == 150, timeout=15), total()


class TestBrokerHttp:
    def test_http_query(self, cluster, tmp_path):
        import json as _json
        import urllib.request

        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, n_segments=1, rows=100)
        assert wait_until(lambda: len(registry.external_view("sales_OFFLINE")) == 1)

        from pinot_tpu.broker.http_api import BrokerHttpServer

        http_srv = BrokerHttpServer(broker)
        http_srv.start()
        try:
            req = urllib.request.Request(
                http_srv.url + "/query/sql",
                data=_json.dumps({"sql": "SELECT COUNT(*) FROM sales"}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = _json.loads(resp.read())
            assert body["resultTable"]["rows"][0][0] == 100
            with urllib.request.urlopen(http_srv.url + "/health", timeout=5) as resp:
                assert _json.loads(resp.read())["status"] == "OK"
        finally:
            http_srv.stop()


class TestServerErrors:
    def test_query_error_does_not_poison_failure_detector(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, n_segments=1, rows=100)
        assert wait_until(lambda: len(registry.external_view("sales_OFFLINE")) == 1)
        r = broker.execute("SELECT nosuchcolumn FROM sales LIMIT 1")
        assert r["exceptions"], r
        assert "SERVER_NOT_RESPONDING" not in r["exceptions"][0]["message"]
        # servers stay healthy: a correct query right after must succeed fully
        r2 = broker.execute("SELECT COUNT(*) FROM sales")
        assert not r2["exceptions"], r2
        assert r2["resultTable"]["rows"][0][0] == 100

    def test_select_star_through_broker(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, n_segments=1, rows=50)
        assert wait_until(lambda: len(registry.external_view("sales_OFFLINE")) == 1)
        r = broker.execute("SELECT * FROM sales LIMIT 5")
        assert not r["exceptions"], r
        assert r["resultTable"]["dataSchema"]["columnNames"] == [
            "region", "product", "amount"
        ]
        assert len(r["resultTable"]["rows"]) == 5
        assert all(len(row) == 3 for row in r["resultTable"]["rows"])


# ---------------------------------------------------------------------------
# ISSUE 10: replica-group assignment, load-aware routing, broker result cache
# ---------------------------------------------------------------------------

TABLE_OFF = "sales_OFFLINE"


def _assignment_by_group(registry, table=TABLE_OFF):
    """{group name: {segment: instance}} from the written assignment."""
    groups = registry.replica_groups(table)
    assign = registry.assignment(table)
    out = {}
    for gname, members in groups.items():
        mset = set(members)
        out[gname] = {
            seg: next((i for i in insts if i in mset), None)
            for seg, insts in assign.items()
        }
    return out


class TestReplicaGroupAssignment:
    def test_every_segment_r_covered(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, n_segments=6, replication=2)
        controller.setup_replica_groups("sales")
        groups = registry.replica_groups(TABLE_OFF)
        assert len(groups) == 2
        # groups partition the live servers (no instance in two groups)
        members = [m for ms in groups.values() for m in ms]
        assert len(members) == len(set(members)) == 3
        # every segment: exactly one copy per group, R copies total
        assign = registry.assignment(TABLE_OFF)
        assert len(assign) == 6
        for seg, insts in assign.items():
            assert len(insts) == 2, (seg, insts)
            for gname, ms in groups.items():
                assert len(set(insts) & set(ms)) == 1, (seg, gname)

    def test_rebalance_on_join_moves_minimum(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, n_segments=8, replication=1)
        controller.setup_replica_groups("sales")
        before = registry.assignment(TABLE_OFF)
        groups_before = registry.replica_groups(TABLE_OFF)
        # a 4th server joins; repair rebuilds groups with minimal movement
        s_new = ServerInstance("server_3", registry,
                               str(tmp_path / "srv3"), device_executor=None)
        s_new.start()
        try:
            controller.run_replica_group_repair()
            after = registry.assignment(TABLE_OFF)
            groups_after = registry.replica_groups(TABLE_OFF)
            # survivors keep their group membership (no leveling can
            # trigger here: R=1 means one group before and after)
            for gname, ms in groups_before.items():
                assert set(ms) <= set(groups_after[gname]), \
                    (gname, ms, groups_after)
            # the new server lands in exactly one group
            placed = [g for g, ms in groups_after.items()
                      if "server_3" in ms]
            assert len(placed) == 1
            # minimal movement: only segments filling the joiner's fair
            # share move — fair share = ceil(8 segments / group size)
            group = groups_after[placed[0]]
            fair = -(-8 // len(group))
            moved = sum(
                1 for seg in before
                if set(before[seg]) != set(after.get(seg, ()))
            )
            assert moved <= fair, (moved, fair, before, after)
            # coverage invariant survives the join
            for seg, insts in after.items():
                assert len(insts) == 1
        finally:
            s_new.stop()

    def test_partition_aware_placement(self, cluster, tmp_path):
        from pinot_tpu.common.table_config import SegmentPartitionConfig

        registry, controller, servers, broker = cluster
        schema = Schema.build(
            name="sales",
            dimensions=[("region", DataType.STRING)],
            metrics=[("store_id", DataType.INT),
                     ("amount", DataType.INT)],
        )
        cfg = TableConfig(
            table_name="sales", replication=1,
            partition=SegmentPartitionConfig(
                column_partition_map={"store_id": ("modulo", 4)}),
        )
        controller.add_table(cfg, schema)
        rng = np.random.default_rng(4)
        # two segments per modulo-partition: co-partitioned segments must
        # co-locate (the broker prunes partition-EQ queries with the same
        # common/pruning.py algebra the server uses — placement has to
        # agree or the pruned route would miss its one holder)
        for i in range(8):
            part = i % 4
            store = np.full(300, part, dtype=np.int64) + \
                4 * rng.integers(0, 20, 300)
            cols = {
                "region": np.array(["na", "eu"])[rng.integers(0, 2, 300)],
                "store_id": store.astype(np.int32),
                "amount": rng.integers(1, 100, 300).astype(np.int32),
            }
            d = str(tmp_path / f"pseg{i}")
            build_segment(schema, cols, d, cfg, f"sales_p{i}")
            controller.upload_segment("sales", d)
        controller.setup_replica_groups("sales")
        records = registry.segments(TABLE_OFF)
        by_group = _assignment_by_group(registry)
        for gname, seg_map in by_group.items():
            # the controller indexes the group list in REGISTRY order
            # (build_replica_groups insertion order), not sorted
            group_list = registry.replica_groups(TABLE_OFF)[gname]
            by_part = {}
            for seg, inst in seg_map.items():
                rec = records[seg]
                assert rec.partition_ids, seg
                pid = int(rec.partition_ids[0])
                by_part.setdefault(pid, set()).add(inst)
                # deterministic pick: partition id -> member
                assert inst == group_list[pid % len(group_list)], \
                    (seg, pid, inst, group_list)
            for pid, insts in by_part.items():
                assert len(insts) == 1, (gname, pid, insts)


class TestLoadAwareRouting:
    def _registry_with_groups(self):
        from pinot_tpu.cluster.registry import InstanceInfo, SegmentRecord

        registry = ClusterRegistry()
        for inst in ("a", "b"):
            registry.register_instance(
                InstanceInfo(instance_id=inst, role=Role.SERVER))
        schema = Schema.build(name="t", dimensions=[("d", DataType.STRING)],
                              metrics=[("m", DataType.INT)])
        registry.add_table(TableConfig(table_name="t"), schema)
        for seg in ("t_s0", "t_s1"):
            registry.add_segment(
                SegmentRecord(name=seg, table="t_OFFLINE", n_docs=10),
                ["a", "b"])
        registry.update_external_view("a", {"t_OFFLINE": ["t_s0", "t_s1"]})
        registry.update_external_view("b", {"t_OFFLINE": ["t_s0", "t_s1"]})
        registry.set_replica_groups("t_OFFLINE",
                                    {"rg_0": ["a"], "rg_1": ["b"]})
        return registry

    def test_least_loaded_group_wins(self):
        from pinot_tpu.broker.broker import FailureDetector, RoutingManager

        registry = self._registry_with_groups()
        rm = RoutingManager(registry, FailureDetector())
        # instance "a" reports a saturated scheduler, "b" reports idle
        rm.loads.observe("a", pressure=8.0)
        rm.loads.observe("b", pressure=0.0)
        picks = set()
        for _ in range(6):
            routing, replicas, info = rm.routing_with_replicas("t_OFFLINE")
            assert info["numReplicaGroupsQueried"] == 1
            assert info["loadScore"] is not None
            picks.add(info["replicaGroup"])
            assert set(routing) == {"b"}, routing
        assert picks == {"rg_1"}

    def test_tied_groups_share_round_robin(self):
        from pinot_tpu.broker.broker import FailureDetector, RoutingManager

        registry = self._registry_with_groups()
        rm = RoutingManager(registry, FailureDetector())
        rm.loads.observe("a", pressure=0.0)
        rm.loads.observe("b", pressure=0.0)
        picks = [rm.routing_with_replicas("t_OFFLINE")[2]["replicaGroup"]
                 for _ in range(8)]
        assert set(picks) == {"rg_0", "rg_1"}

    def test_reservation_counts_concurrent_arrivals(self):
        from pinot_tpu.broker.broker import FailureDetector, RoutingManager

        registry = self._registry_with_groups()
        rm = RoutingManager(registry, FailureDetector())
        rm.loads.observe("a", pressure=0.0)
        rm.loads.observe("b", pressure=0.0)
        # two reserving queries that never release must land on DIFFERENT
        # groups: the second pick sees the first's outstanding count
        _, _, i1 = rm.routing_with_replicas("t_OFFLINE", reserve=True)
        _, _, i2 = rm.routing_with_replicas("t_OFFLINE", reserve=True)
        assert {i1["replicaGroup"], i2["replicaGroup"]} == {"rg_0", "rg_1"}
        for info in (i1, i2):
            for inst in info.get("reserved", ()):
                rm.release([inst])

    def test_unhealthy_group_skipped(self):
        from pinot_tpu.broker.broker import FailureDetector, RoutingManager

        registry = self._registry_with_groups()
        det = FailureDetector(initial_backoff_s=30.0)
        rm = RoutingManager(registry, det)
        rm.loads.observe("a", pressure=0.0)
        rm.loads.observe("b", pressure=5.0)  # loaded BUT healthy
        det.mark_failure("a")  # idle group's only member is down
        for _ in range(4):
            routing, _, info = rm.routing_with_replicas("t_OFFLINE")
            assert info["replicaGroup"] == "rg_1"
            assert set(routing) == {"b"}


class TestBrokerResultCache:
    def test_hit_miss_parity_and_invalidation(self, cluster, tmp_path):
        from pinot_tpu.common import freshness

        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, n_segments=3, rows=500)
        assert wait_until(
            lambda: len(registry.external_view(TABLE_OFF)) == 3)
        cbroker = Broker(registry, broker_id="cache_broker",
                         timeout_s=10.0, result_cache=True)
        try:
            sql = ("SELECT region, COUNT(*), SUM(amount) FROM sales "
                   "GROUP BY region ORDER BY region")
            miss = cbroker.execute(sql)
            assert not miss["exceptions"], miss
            assert miss["resultCacheHit"] is False
            hit = cbroker.execute(sql)
            assert hit["resultCacheHit"] is True
            # parity: hit == miss == cache-off broker, bit-exact
            off = broker.execute(sql)
            assert hit["resultTable"]["rows"] == \
                miss["resultTable"]["rows"] == off["resultTable"]["rows"]
            assert cbroker.result_cache.stats()["hits"] == 1
            # a routing change (new segment uploaded) invalidates: the
            # next execution is a MISS and sees the new rows
            schema = registry.table_schema(TABLE_OFF)
            rng = np.random.default_rng(77)
            cols = {
                "region": np.array(["apac"] * 40),
                "product": np.array([f"p{j}" for j in range(50)])[
                    rng.integers(0, 50, 40)],
                "amount": np.full(40, 7, dtype=np.int32),
            }
            d = str(tmp_path / "late_seg")
            build_segment(schema, cols, d,
                          TableConfig(table_name="sales"), "sales_late")
            controller.upload_segment("sales", d)
            assert wait_until(
                lambda: len(registry.external_view(TABLE_OFF)) == 4)
            r2 = cbroker.execute(sql)
            assert r2["resultCacheHit"] is False
            assert r2["resultTable"]["rows"] != hit["resultTable"]["rows"]
            # an epoch bump (in-place mutation, e.g. a consuming append)
            # invalidates even with the segment set unchanged. Servers
            # report epochs via heartbeat + piggyback; in-process they
            # share the freshness module, so bump + heartbeat directly.
            assert cbroker.execute(sql)["resultCacheHit"] is True  # r2 filled
            freshness.bump("sales")
            for s in servers:
                registry.heartbeat(s.instance_id,
                                   table_epochs=freshness.snapshot())
            time.sleep(0.3)  # ride out the broker's instances memo
            r3 = cbroker.execute(sql)
            assert r3["resultCacheHit"] is False
            assert r3["resultTable"]["rows"] == \
                r2["resultTable"]["rows"]
            assert cbroker.result_cache.stats()["invalidations"] >= 1
        finally:
            cbroker.close()
            freshness.reset()

    def test_opt_out_and_uncacheable_queries(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, n_segments=1, rows=100)
        assert wait_until(
            lambda: len(registry.external_view(TABLE_OFF)) == 1)
        cbroker = Broker(registry, broker_id="cache_broker2",
                         timeout_s=10.0, result_cache=True)
        try:
            sql = "SELECT COUNT(*) FROM sales"
            cbroker.execute(sql)
            assert cbroker.execute(sql)["resultCacheHit"] is True
            r = cbroker.execute("SET useResultCache = false; " + sql)
            assert "resultCacheHit" not in r
            # cache-off broker can opt IN per query
            r2 = broker.execute("SET useResultCache = true; " + sql)
            assert r2["resultCacheHit"] is False
            r3 = broker.execute("SET useResultCache = true; " + sql)
            assert r3["resultCacheHit"] is True
        finally:
            cbroker.close()

    def test_epoch_bump_seams(self, tmp_path):
        """The three in-place mutation seams (append, upsert-invalidate,
        seal) and chunklet promotion all bump the table freshness epoch —
        the contract the broker cache's staleness view rests on."""
        from pinot_tpu.common import freshness
        from pinot_tpu.common.table_config import ChunkletConfig
        from pinot_tpu.storage.mutable import MutableSegment

        freshness.reset()
        schema = Schema.build(
            name="rt", dimensions=[("zone", DataType.STRING)],
            metrics=[("fare", DataType.INT)],
            primary_key_columns=["zone"],
        )
        # ChunkletIndex floors rows_per_chunklet at 1024, so index past
        # that to make promote() actually freeze a block
        cfg = TableConfig(
            table_name="rt",
            chunklets=ChunkletConfig(enabled=True, rows_per_chunklet=1024,
                                     device_min_rows=0))
        seg = MutableSegment(schema, "rt__0", cfg, enable_upsert=True)
        assert freshness.epoch("rt") == 0
        seg.index({"zone": "z1", "fare": 3})
        e1 = freshness.epoch("rt")
        assert e1 >= 1
        seg.index_batch([{"zone": f"z{i}", "fare": i} for i in range(1100)])
        e2 = freshness.epoch("rt")
        assert e2 > e1
        if seg.chunklet_index is not None:
            made = seg.chunklet_index.promote()
            assert made >= 1
            assert freshness.epoch("rt") > e2
        e3 = freshness.epoch("rt")
        seg.invalidate(0)
        assert freshness.epoch("rt") > e3
        e4 = freshness.epoch("rt")
        seg.seal(str(tmp_path / "sealed"))
        assert freshness.epoch("rt") > e4
        freshness.reset()


class TestClusterQpsSmoke:
    def test_three_server_replica_group_qps(self, cluster, tmp_path):
        """3 in-process servers over real gRPC, replica groups R=3 (one
        full copy each): concurrent traffic routes whole queries to single
        groups, spreads across all three, and answers correctly."""
        import threading

        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, n_segments=4, rows=1500,
                       replication=3)
        controller.setup_replica_groups("sales")
        assert wait_until(lambda: all(
            len(v) == 3
            for v in registry.external_view(TABLE_OFF).values()) and len(
            registry.external_view(TABLE_OFF)) == 4, timeout=30)
        expected = broker.execute(
            "SELECT region, COUNT(*) FROM sales GROUP BY region "
            "ORDER BY region")
        assert not expected["exceptions"]
        assert expected["numReplicaGroupsQueried"] == 1
        assert expected.get("loadScore") is not None
        rows = expected["resultTable"]["rows"]
        errors = []
        groups_seen = set()
        lock = threading.Lock()

        def worker():
            for _ in range(8):
                r = broker.execute(
                    "SELECT region, COUNT(*) FROM sales GROUP BY region "
                    "ORDER BY region")
                with lock:
                    if r.get("exceptions") or \
                            r["resultTable"]["rows"] != rows:
                        errors.append(r)
                    groups_seen.add(r.get("replicaGroup"))

        ts = [threading.Thread(target=worker) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errors, errors[:1]
        # ties share round-robin traffic: all three groups serve
        assert groups_seen == {"rg_0", "rg_1", "rg_2"}, groups_seen
