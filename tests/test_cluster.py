"""Cluster integration: controller + servers + broker over real gRPC.

Reference analogs: pinot-integration-test-base ClusterTest (all roles in one
process, real transport), OfflineClusterIntegrationTest (push segments,
query via broker), MultiNodesOfflineClusterIntegrationTest, LLCRealtime-
ClusterIntegrationTest (stream → consuming → commit → broker-visible),
ChaosMonkey-style server kill with partial results, rebalance, retention.
"""

import time

import numpy as np
import pytest

from pinot_tpu.broker.broker import Broker
from pinot_tpu.cluster.registry import ClusterRegistry, Role, SegmentState
from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import StreamConfig, TableConfig, TableType
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.server import ServerInstance
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.stream.memory_stream import TopicRegistry


def wait_until(cond, timeout=10.0, interval=0.05):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def cluster(tmp_path):
    registry = ClusterRegistry()
    controller = Controller(registry, str(tmp_path / "deepstore"))
    servers = [
        ServerInstance(f"server_{i}", registry, str(tmp_path / f"srv{i}"),
                       device_executor=None)
        for i in range(3)
    ]
    for s in servers:
        s.start()
    broker = Broker(registry, timeout_s=10.0)
    yield registry, controller, servers, broker
    broker.close()
    for s in servers:
        try:
            s.stop()
        except Exception:
            pass


def _offline_table(tmp_path, controller, n_segments=4, rows=2000, replication=2):
    schema = Schema.build(
        name="sales",
        dimensions=[("region", DataType.STRING), ("product", DataType.STRING)],
        metrics=[("amount", DataType.INT)],
    )
    cfg = TableConfig(table_name="sales", replication=replication)
    controller.add_table(cfg, schema)
    rng = np.random.default_rng(9)
    all_cols = []
    for i in range(n_segments):
        cols = {
            "region": np.array(["na", "eu", "apac"])[rng.integers(0, 3, rows)],
            "product": np.array([f"p{j}" for j in range(50)])[rng.integers(0, 50, rows)],
            "amount": rng.integers(1, 500, rows).astype(np.int32),
        }
        all_cols.append(cols)
        d = str(tmp_path / f"upload_s{i}")
        build_segment(schema, cols, d, cfg, f"sales_s{i}")
        controller.upload_segment("sales", d)
    return schema, cfg, all_cols


class TestOfflineCluster:
    def test_push_and_query(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _, _, all_cols = _offline_table(tmp_path, controller)
        # servers pick up assignments via sync loop
        assert wait_until(lambda: sum(
            len(s.engine.tables.get("sales_OFFLINE").segments) if s.engine.tables.get("sales_OFFLINE") else 0
            for s in servers
        ) >= 8)  # 4 segments x 2 replicas

        total = sum(int(c["amount"].sum()) for c in all_cols)
        r = broker.execute("SELECT COUNT(*), SUM(amount) FROM sales")
        assert not r["exceptions"], r
        assert r["resultTable"]["rows"][0] == [8000, total]
        assert r["numServersResponded"] >= 1
        # every segment counted exactly once despite replication
        assert r["numSegmentsQueried"] == 4
        # case-insensitive table resolution at the broker
        # (BaseBrokerRequestHandler.java:245-254 / TableCache ignore-case)
        for variant in ("SALES", "Sales", "sAlEs_OFFLINE"):
            r2 = broker.execute(f"SELECT COUNT(*) FROM {variant}")
            assert not r2["exceptions"], (variant, r2)
            assert r2["resultTable"]["rows"][0][0] == 8000

    def test_group_by_through_broker(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _, _, all_cols = _offline_table(tmp_path, controller)
        assert wait_until(lambda: len(registry.external_view("sales_OFFLINE")) == 4)
        r = broker.execute(
            "SELECT region, COUNT(*), SUM(amount) FROM sales GROUP BY region ORDER BY region"
        )
        assert not r["exceptions"], r
        import collections

        want = collections.Counter()
        wsum = collections.Counter()
        for c in all_cols:
            for reg, amt in zip(c["region"], c["amount"]):
                want[reg] += 1
                wsum[reg] += int(amt)
        got = {row[0]: (row[1], row[2]) for row in r["resultTable"]["rows"]}
        assert got == {k: (want[k], wsum[k]) for k in want}

    def test_server_death_partial_results(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, replication=1)
        assert wait_until(lambda: len(registry.external_view("sales_OFFLINE")) == 4)
        ok = broker.execute("SELECT COUNT(*) FROM sales")
        assert not ok["exceptions"]
        # kill one server hard (ChaosMonkey): with replication=1 its segments
        # are lost → partial results + SERVER_NOT_RESPONDING exception
        victim = next(
            s for s in servers if registry.assigned_segments(s.instance_id)
        )
        victim.transport.stop(grace=0)
        r = broker.execute("SELECT COUNT(*) FROM sales")
        assert r.get("partialResult") is True
        assert any("SERVER_NOT_RESPONDING" in e["message"] for e in r["exceptions"])
        assert r["resultTable"]["rows"][0][0] < 8000  # partial data

    def test_failover_with_replication(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, replication=2)
        assert wait_until(lambda: sum(
            len(v) for v in registry.external_view("sales_OFFLINE").values()
        ) >= 8)
        victim = next(s for s in servers if registry.assigned_segments(s.instance_id))
        victim.transport.stop(grace=0)
        # first query may be partial (failure detected); retried queries
        # route around the dead server to the surviving replicas
        deadline = time.time() + 10
        while time.time() < deadline:
            r = broker.execute("SELECT COUNT(*) FROM sales")
            if not r.get("exceptions") and r["resultTable"]["rows"][0][0] == 8000:
                break
            time.sleep(0.1)
        assert r["resultTable"]["rows"][0][0] == 8000, r

    def test_rebalance_after_server_join(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, replication=1)
        late = ServerInstance("server_late", registry, str(tmp_path / "late"),
                              device_executor=None)
        late.start()
        try:
            mapping = controller.rebalance("sales")
            hosts = {i for insts in mapping.values() for i in insts}
            # late server participates after rebalance OR load stays balanced
            counts = {}
            for insts in mapping.values():
                for i in insts:
                    counts[i] = counts.get(i, 0) + 1
            assert max(counts.values()) - min(counts.values()) <= 1
            assert wait_until(
                lambda: broker.execute("SELECT COUNT(*) FROM sales")
                .get("resultTable", {}).get("rows", [[0]])[0][0] == 8000
            )
        finally:
            late.stop()

    def test_retention(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        schema = Schema.build(
            name="logs",
            dimensions=[("k", DataType.STRING)],
            metrics=[("v", DataType.INT)],
            datetimes=[("ts", DataType.LONG)],
        )
        cfg = TableConfig(table_name="logs", retention_days=7, time_column="ts")
        controller.add_table(cfg, schema)
        now = int(time.time() * 1000)
        old_ts = now - 30 * 86_400_000
        for name, ts in (("old", old_ts), ("new", now)):
            d = str(tmp_path / f"logs_{name}")
            build_segment(
                schema,
                {"k": ["a"] * 10, "v": list(range(10)), "ts": [ts] * 10},
                d, cfg, f"logs_{name}",
            )
            controller.upload_segment("logs", d)
        assert len(registry.segments("logs_OFFLINE")) == 2
        dropped = controller.run_retention()
        assert ("logs_OFFLINE", "logs_old") in dropped
        assert "logs_new" in registry.segments("logs_OFFLINE")


class TestRealtimeCluster:
    def test_stream_to_broker_visibility(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        TopicRegistry.delete("clicks")
        topic = TopicRegistry.create("clicks", 2)
        schema = Schema.build(
            name="clicks",
            dimensions=[("page", DataType.STRING)],
            metrics=[("n", DataType.INT)],
        )
        cfg = TableConfig(
            table_name="clicks", table_type=TableType.REALTIME,
            stream=StreamConfig(
                stream_type="memory", topic="clicks", decoder="json",
                segment_flush_threshold_rows=60, segment_flush_threshold_seconds=3600,
            ),
        )
        controller.add_table(cfg, schema)
        for i in range(200):
            topic.publish_json({"page": f"page{i % 8}", "n": 1}, partition=i % 2)

        def broker_count():
            r = broker.execute("SELECT COUNT(*) FROM clicks")
            if r.get("exceptions"):
                return -1
            return r["resultTable"]["rows"][0][0]

        assert wait_until(lambda: broker_count() == 200, timeout=15), broker_count()
        # commits happened and sealed segments are registered ONLINE
        assert wait_until(lambda: any(
            rec.state == SegmentState.ONLINE
            for rec in registry.segments("clicks_REALTIME").values()
        ))
        r = broker.execute(
            "SELECT page, COUNT(*) FROM clicks GROUP BY page ORDER BY page LIMIT 10"
        )
        assert [row[1] for row in r["resultTable"]["rows"]] == [25] * 8


    def test_kill_consuming_server_no_loss(self, cluster, tmp_path):
        """Multi-replica consumption survives a consumer death: the replica
        keeps serving, the controller re-homes the dead server's partitions,
        and every row stays queryable exactly once (SegmentCompletionManager
        + RealtimeSegmentValidationManager semantics)."""
        registry, controller, servers, broker = cluster
        TopicRegistry.delete("mrclicks")
        topic = TopicRegistry.create("mrclicks", 1)
        schema = Schema.build(
            name="mrclicks",
            dimensions=[("page", DataType.STRING)],
            metrics=[("n", DataType.INT)],
        )
        cfg = TableConfig(
            table_name="mrclicks", table_type=TableType.REALTIME, replication=2,
            stream=StreamConfig(
                stream_type="memory", topic="mrclicks", decoder="json",
                segment_flush_threshold_rows=40, segment_flush_threshold_seconds=3600,
            ),
        )
        controller.add_table(cfg, schema)
        pa = registry.partition_assignment("mrclicks_REALTIME")
        assert all(len(v) == 2 for v in pa.values())

        def broker_count():
            r = broker.execute("SELECT COUNT(*) FROM mrclicks")
            if r.get("exceptions"):
                return -1
            return r["resultTable"]["rows"][0][0]

        for i in range(100):
            topic.publish_json({"page": f"p{i % 4}", "n": 1})
        assert wait_until(lambda: broker_count() == 100, timeout=20), broker_count()

        # kill one of the consuming replicas hard, mid-stream
        victims = set(pa["0"])
        victim = next(s for s in servers if s.instance_id in victims)
        victim.transport.stop(grace=0)
        victim._stop.set()  # sync loop (and its consumers' publishes) halt
        for mgr in victim._realtime_managers.values():
            mgr.stop(commit_remaining=False)
        for i in range(100):
            topic.publish_json({"page": f"p{i % 4}", "n": 1})
        controller.run_realtime_repair()

        deadline = time.time() + 20
        count = -1
        while time.time() < deadline:
            count = broker_count()
            if count == 200:
                break
            time.sleep(0.1)
        assert count == 200, count
        r = broker.execute(
            "SELECT page, COUNT(*) FROM mrclicks GROUP BY page ORDER BY page"
        )
        assert [row[1] for row in r["resultTable"]["rows"]] == [50] * 4


class TestHybridTable:
    def test_time_boundary_split(self, cluster, tmp_path):
        """Hybrid table: offline covers old time range, realtime covers new;
        the broker splits at the boundary so overlapping rows dedupe
        (TimeBoundaryManager + BaseBrokerRequestHandler.java:387-395)."""
        registry, controller, servers, broker = cluster
        schema = Schema.build(
            name="metrics",
            dimensions=[("host", DataType.STRING)],
            metrics=[("v", DataType.INT)],
            datetimes=[("ts", DataType.LONG)],
        )
        off_cfg = TableConfig(table_name="metrics", time_column="ts")
        controller.add_table(off_cfg, schema)
        # offline segment: ts 0..99 (100 rows)
        d = str(tmp_path / "metrics_off")
        build_segment(
            schema,
            {"host": ["h1"] * 100, "v": [1] * 100, "ts": list(range(100))},
            d, off_cfg, "metrics_off_0",
        )
        controller.upload_segment("metrics", d)

        TopicRegistry.delete("metrics_stream")
        topic = TopicRegistry.create("metrics_stream", 1)
        rt_cfg = TableConfig(
            table_name="metrics", table_type=TableType.REALTIME, time_column="ts",
            stream=StreamConfig(
                stream_type="memory", topic="metrics_stream", decoder="json",
                segment_flush_threshold_rows=10_000,
                segment_flush_threshold_seconds=3600,
            ),
        )
        controller.add_table(rt_cfg, schema)
        # realtime overlaps offline for ts 80..99 (late replay), then extends
        for ts in range(80, 150):
            topic.publish_json({"host": "h1", "v": 1, "ts": ts})

        def total():
            r = broker.execute("SELECT COUNT(*) FROM metrics")
            if r.get("exceptions"):
                return -1
            return r["resultTable"]["rows"][0][0]

        # boundary = offline max ts (99): offline answers ts<=99 (100 rows),
        # realtime answers ts>99 (50 rows) — overlap NOT double counted
        assert wait_until(lambda: total() == 150, timeout=15), total()


class TestBrokerHttp:
    def test_http_query(self, cluster, tmp_path):
        import json as _json
        import urllib.request

        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, n_segments=1, rows=100)
        assert wait_until(lambda: len(registry.external_view("sales_OFFLINE")) == 1)

        from pinot_tpu.broker.http_api import BrokerHttpServer

        http_srv = BrokerHttpServer(broker)
        http_srv.start()
        try:
            req = urllib.request.Request(
                http_srv.url + "/query/sql",
                data=_json.dumps({"sql": "SELECT COUNT(*) FROM sales"}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = _json.loads(resp.read())
            assert body["resultTable"]["rows"][0][0] == 100
            with urllib.request.urlopen(http_srv.url + "/health", timeout=5) as resp:
                assert _json.loads(resp.read())["status"] == "OK"
        finally:
            http_srv.stop()


class TestServerErrors:
    def test_query_error_does_not_poison_failure_detector(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, n_segments=1, rows=100)
        assert wait_until(lambda: len(registry.external_view("sales_OFFLINE")) == 1)
        r = broker.execute("SELECT nosuchcolumn FROM sales LIMIT 1")
        assert r["exceptions"], r
        assert "SERVER_NOT_RESPONDING" not in r["exceptions"][0]["message"]
        # servers stay healthy: a correct query right after must succeed fully
        r2 = broker.execute("SELECT COUNT(*) FROM sales")
        assert not r2["exceptions"], r2
        assert r2["resultTable"]["rows"][0][0] == 100

    def test_select_star_through_broker(self, cluster, tmp_path):
        registry, controller, servers, broker = cluster
        _offline_table(tmp_path, controller, n_segments=1, rows=50)
        assert wait_until(lambda: len(registry.external_view("sales_OFFLINE")) == 1)
        r = broker.execute("SELECT * FROM sales LIMIT 5")
        assert not r["exceptions"], r
        assert r["resultTable"]["dataSchema"]["columnNames"] == [
            "region", "product", "amount"
        ]
        assert len(r["resultTable"]["rows"]) == 5
        assert all(len(row) == 3 for row in r["resultTable"]["rows"])
