#!/bin/sh
# Role dispatcher (pinot-admin.sh Start<Role>Command analog).
set -e

ADMIN="python -m pinot_tpu.tools.admin"
ID_FLAG=""
[ -n "$PINOT_ID" ] && ID_FLAG="--id $PINOT_ID"
# advertised/bind host: the container hostname resolves to the container
# IP for peers (compose service name / pod DNS); brokers bind 0.0.0.0 so
# published ports work from outside
HOST="${PINOT_HOST:-$(hostname)}"
# per-instance data dirs on the shared volume: two servers must never
# share a segment directory
DATA_DIR="$PINOT_DATA_DIR/${PINOT_ID:-default}"

case "$ROLE" in
  controller)
    exec $ADMIN start-controller --registry "$PINOT_REGISTRY" \
        --deep-store "$PINOT_DEEP_STORE" $ID_FLAG "$@" ;;
  server)
    exec $ADMIN start-server --registry "$PINOT_REGISTRY" \
        --data-dir "$DATA_DIR" --host "$HOST" $ID_FLAG "$@" ;;
  broker)
    exec $ADMIN start-broker --registry "$PINOT_REGISTRY" \
        --host "${PINOT_HOST:-0.0.0.0}" $ID_FLAG "$@" ;;
  minion)
    exec $ADMIN start-minion --registry "$PINOT_REGISTRY" \
        --deep-store "$PINOT_DEEP_STORE" \
        --work-dir "/var/pinot/minionwork/${PINOT_ID:-default}" $ID_FLAG "$@" ;;
  quickstart)
    exec $ADMIN quickstart "$@" ;;
  *)
    echo "unknown ROLE '$ROLE' (controller|server|broker|minion|quickstart)" >&2
    exit 2 ;;
esac
